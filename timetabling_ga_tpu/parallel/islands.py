"""Island-model GA over a TPU device mesh.

TPU-native replacement for the reference's MPI island model
(ga.cpp:370-541). The mapping, per SURVEY C15/C17 and section 5:

  MPI rank / island            -> shard of the population along mesh axis
                                  "island" (`shard_map` over a 1-D Mesh)
  MPI_Bcast of the problem     -> replicated ProblemArrays (device_put)
  per-rank seed arithmetic     -> `jax.random.fold_in(key, island_index)`
                                  (replaces `abs(seed+i*(seed/10))`,
                                  ga.cpp:412)
  MPI_Sendrecv ring migration  -> `lax.ppermute`: best solution forward
                                  (tag 2, ga.cpp:522-526), second-best
                                  backward (tag 4, ga.cpp:530-533)
  immigrants replace 2 worst   -> scatter into the sorted population's
                                  last two rows (ga.cpp:344-346, 528, 535)
  MPI_Allreduce(MIN)           -> `lax.pmin` (ga.cpp:237, 248)
  MPI_Barrier pairs            -> none needed; collective semantics
                                  synchronize (SURVEY section 5)

The reference migrates when a per-thread counter hits 100 local periods
(offset 50), making wall-clock cadence depend on thread count — a
scheduling quirk, not a capability (SURVEY section 3.5). Here the cadence
is explicit: `gens_per_epoch` generations between migrations.

Multi-host scaling: the same `Mesh` spans hosts under `jax.distributed`
(ICI within a slice, DCN across slices) with no code change — the mesh
axis is the single abstraction, exactly as the scaling-book recipe
prescribes.
"""

from __future__ import annotations

import collections
import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from timetabling_ga_tpu.compat import shard_map

# stdlib-only layout constants + host decode of the quality block the
# runners append to the telemetry leaf (README "Search-quality
# observatory"); the device-side packing lives HERE, with the leaf
from timetabling_ga_tpu.obs import prof as obs_prof
from timetabling_ga_tpu.obs import quality as obs_quality
from timetabling_ga_tpu.ops import fitness, ga


AXIS = "island"

# Trace-time counters, keyed by program tag ("lane_runner", "lane_init",
# ...): the builders below bump the tag INSIDE the to-be-jitted Python
# function, so the count increments exactly when XLA (re)traces — i.e.
# once per compiled (program, shape) pair and zero times on a cache hit.
# This is the observable behind the serve subsystem's bucket guarantee
# (two different-size instances in one bucket => ONE trace per program;
# tests/test_serve.py, bench.py extra.serve bucket_compiles).
TRACE_COUNTS: collections.Counter = collections.Counter()


def _mark_trace(tag: str) -> None:
    TRACE_COUNTS[tag] += 1


def _donate(fn, donate: bool, argnum: int, name: str = None):
    """jit a runner, optionally donating its PopState/LahcState argument.

    Donation lets XLA alias the (up to pop 32768 x events) population
    buffers between dispatches instead of copying them — the state
    tensors dominate device memory traffic at scale, and every runner
    here is of the shape `state -> state` with identical shapes and
    shardings on both sides, the ideal aliasing case. Opt-in
    (donate=False default) because a donated input is DELETED at
    dispatch: callers that reuse the input state afterwards (tests,
    exploratory notebooks) would hit 'Array has been deleted'. The
    engine opts in and never reuses a dispatched state (tt-analyze
    TT203 is the lint guard for that discipline).

    `name` becomes the compiled HLO module's name (jit_<name>). Every
    builder here names its variant after its STATIC build parameters:
    an engine run compiles several structurally different programs
    from functions all called `_run`, and XLA would name every one of
    them `jit__run` — the tt-prof sidecar (obs/prof.py) joins trace
    events to phases by (module, op), so same-named variants would
    shadow each other's op tables and the executed variant's ops could
    look unattributable. Purely a label: no cache key, record, or
    numeric depends on it."""
    return _named_jit(fn, name, donate_argnums=(argnum,) if donate else ())


def _named_jit(fn, name: str = None, **jit_kwargs):
    """jax.jit with an explicit HLO module name (see _donate)."""
    if name is not None:
        try:
            fn.__name__ = name
            fn.__qualname__ = name
        except (AttributeError, TypeError):
            pass
    return jax.jit(fn, **jit_kwargs)


def delete_state(state) -> None:
    """Best-effort device-buffer teardown for a poisoned or abandoned
    state pytree (the engine's fault-recovery path, README "Fault
    tolerance"). After a transient device failure the in-flight state's
    buffers are in an unknown condition — and with donation enabled
    (`_donate`) the FAILED dispatch may already have deleted its input
    aliases, so a leaf may legitimately be gone. Deleting each live
    leaf releases device memory before rehydration re-places the
    population from the host snapshot; every error is swallowed because
    the buffers are being discarded either way, and a donated-then-
    killed buffer must never be re-read (only dropped)."""
    if state is None:
        return
    for leaf in jax.tree.leaves(state):
        try:
            leaf.delete()
        except Exception:
            pass


def make_mesh(n_islands: int = None, devices=None) -> Mesh:
    """1-D device mesh with axis "island" (the reference's MPI_Comm_size
    world, ga.cpp:379)."""
    if devices is None:
        devices = jax.devices()
    if n_islands is not None:
        devices = devices[:n_islands]
    import numpy as np
    return Mesh(np.array(devices), (AXIS,))


def pad_lanes(mesh: Mesh, n_lanes: int) -> int:
    """Smallest lane count >= `n_lanes` that `local_islands` accepts on
    `mesh` (a multiple of the device count). The serve scheduler sizes
    its dispatch width with this: jobs fill the first `n_lanes` lanes
    and the padding lanes run zero-generation filler whose
    device-seconds the tt-meter split books as overhead
    (serve/scheduler.py)."""
    n_dev = mesh.devices.size
    return ((max(1, n_lanes) + n_dev - 1) // n_dev) * n_dev


def local_islands(mesh: Mesh, n_islands: int = None) -> int:
    """Islands per device. n_islands may EXCEED the device count (the
    analogue of running several MPI ranks per node — mpirun oversubscribes
    cores exactly this way): each device then carries
    L = n_islands / n_devices vmapped local islands, and the migration
    ring runs within-device by rolls and across devices by ppermute at
    the shard boundary. Must divide evenly."""
    if n_islands is None:
        return 1
    n_dev = mesh.devices.size
    if n_islands % n_dev:
        raise ValueError(f"n_islands={n_islands} must be a multiple of "
                         f"the device count {n_dev}")
    return n_islands // n_dev


def _blocks(state: ga.PopState, L: int, pop: int) -> ga.PopState:
    """(L*pop, ...) flat shard -> (L, pop, ...) per-island blocks."""
    return jax.tree.map(
        lambda x: x.reshape((L, pop) + x.shape[1:]), state)


def _flat(state: ga.PopState) -> ga.PopState:
    return jax.tree.map(
        lambda x: x.reshape((-1,) + x.shape[2:]), state)


def init_island_population(pa, key, mesh: Mesh, pop_size: int,
                           cfg: ga.GAConfig = None,
                           n_islands: int = None) -> ga.PopState:
    """Initialize every island's population directly on its own device.

    Global state shape is (n_islands * pop_size, E) sharded along axis 0
    (island-major; device d holds islands [d*L, (d+1)*L)); each island
    draws from `fold_in(key, global_island_index)` so populations are
    independent (divergence from the reference's broadcast-identical
    initial populations, ga.cpp:429-444; SURVEY C17). When
    `cfg.init_sweeps > 0` the initial populations are sweep-LS-polished
    on-device (the reference's initial localSearch, ga.cpp:429-434)."""
    L = local_islands(mesh, n_islands)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P()),
        out_specs=ga.PopState(slots=P(AXIS), rooms=P(AXIS),
                              penalty=P(AXIS), hcv=P(AXIS), scv=P(AXIS)),
        # check_vma=False: the varying-manual-axes checker rejects
        # lax.switch/scan carries whose tags mix island-varying keys with
        # invariant constants (JAX suggests this workaround in the error).
        check_vma=False)
    def _init(pa_, key_):
        base = lax.axis_index(AXIS) * L
        keys = jax.vmap(
            lambda l: jax.random.fold_in(key_, base + l))(
                jnp.arange(L, dtype=jnp.int32))
        st = jax.vmap(
            lambda k: ga.init_population(pa_, k, pop_size, cfg))(keys)
        return _flat(st)

    return _init(pa, key)


@obs_prof.scope("tt.migrate")
def _migrate(state: ga.PopState, n_islands: int, L: int = 1,
             return_gain: bool = False):
    """Bidirectional ring migration of 1 migrant each way over ALL
    n_islands islands (device-resident local islands included).

    `return_gain=True` (the quality observatory) additionally returns a
    (L,) int32 vector of each local island's REPORTED-best improvement
    across this exchange (`_reported_i32` before minus after, clamped
    at 0 — replacement of the two worst rows can only leave the best
    equal or better): the live answer to "is migration earning its
    ppermute". Derived from the sorted blocks the exchange already
    holds — no new collectives (tt-analyze TT604 lints that), no RNG,
    trajectory untouched.

    Best solution to the next island, second-best to the previous
    (ga.cpp:522-535); immigrants overwrite the two worst rows
    (ga.cpp:528, 535, deserialize target ga.cpp:344-346). Each island's
    population is (penalty, scv)-sorted (best first), so rows 0/1 are
    the emigrants and rows -1/-2 the victims. Ring edges between local
    islands of one device are rolls; the two shard-boundary edges ride
    ppermute — collectives only where the topology actually crosses
    devices (ICI traffic = 2 migrants per device per exchange regardless
    of L).

    Populations smaller than 3 skip migration entirely: with P <= 2 a
    victim row aliases the BEST row (at P == 1 both writes land on the
    island's only individual; at P == 2 the backward immigrant lands on
    row 0), so migration would destroy the island's best (ADVICE round
    3). At P == 3 row 1 is both an emigrant and a victim, but emigrants
    are snapshotted before the writes and rows 1-2 really are the two
    worst of three — the reference's own semantics for that size
    (ga.cpp:344-346) — so P == 3 migrates normally. The reference
    itself never goes below popSize 10 (ga.cpp:64). The native twin
    (tt_cpu --islands) applies the same P >= 3 guard."""
    pop = state.penalty.shape[0] // L
    if pop < 3:
        if return_gain:
            return state, jnp.zeros((L,), jnp.int32)
        return state
    n_dev = max(1, n_islands // L)
    fwd = [(i, (i + 1) % n_dev) for i in range(n_dev)]
    bwd = [(i, (i - 1) % n_dev) for i in range(n_dev)]

    blk = _blocks(state, L, pop)
    rep_before = _reported_i32(blk.hcv[:, 0], blk.scv[:, 0])  # (L,)
    best = jax.tree.map(lambda x: x[:, 0], blk)    # (L, ...) emigrants
    second = jax.tree.map(lambda x: x[:, 1], blk)

    # forward ring: local island l receives best of island l-1; island 0
    # receives the PREVIOUS device's island L-1 via ppermute
    imm_f = jax.tree.map(
        lambda b: jnp.roll(b, 1, axis=0).at[0].set(
            lax.ppermute(b[L - 1], AXIS, fwd)), best)
    # backward ring: island l receives second-best of island l+1; island
    # L-1 receives the NEXT device's island 0
    imm_b = jax.tree.map(
        lambda s: jnp.roll(s, -1, axis=0).at[L - 1].set(
            lax.ppermute(s[0], AXIS, bwd)), second)

    blk = jax.tree.map(
        lambda x, a, b: x.at[:, -1].set(a).at[:, -2].set(b),
        blk, imm_f, imm_b)
    # restore each island's sorted order (replacement + sort,
    # ga.cpp:580-585), by the reported-metric order (penalty, scv)
    order = jax.vmap(fitness.lex_order)(blk.penalty, blk.scv)
    blk = jax.tree.map(
        lambda x: jnp.take_along_axis(
            x, order.reshape(order.shape + (1,) * (x.ndim - 2)), axis=1),
        blk)
    if return_gain:
        rep_after = _reported_i32(blk.hcv[:, 0], blk.scv[:, 0])
        return _flat(blk), jnp.maximum(rep_before - rep_after, 0)
    return _flat(blk)


def make_island_runner(mesh: Mesh, cfg: ga.GAConfig, n_epochs: int,
                       gens_per_epoch: int, n_islands: int = None,
                       donate: bool = False, trace_mode: str = "full",
                       quality: bool = False):
    """Build the jitted multi-island evolution step.

    Returns `run(pa, key, state) -> (state, best_trace, global_best)`:
      - state: global PopState sharded over the mesh
      - best_trace (trace_mode="full"): (n_islands, n_epochs,
        gens_per_epoch, 2) int32 — per-GENERATION (hcv, scv) of each
        island's best individual, tracked on-device inside the scan so
        mid-epoch improvements are visible to the JSONL logEntry
        protocol (ga.cpp:203-228) without any per-epoch host fetch; the
        host reads the whole trace once per dispatch
      - best_trace (trace_mode="deltas"/"stats"): the ON-DEVICE
        compressed form (_compress_trace): (n_islands,
        trace_leaf_width(...)) int32 of improvement events + count
        [+ moments] — the telemetry leaf shrinks from O(gens) to O(K)
        per island while the emitted record stream stays identical
      - global_best: scalar = pmin over islands of the final best penalty
        (the reference's MPI_Allreduce MIN, ga.cpp:237)
    One dispatch runs n_epochs x gens_per_epoch generations on all islands
    including all migrations. `n_islands` may exceed the device count
    (local_islands: vmapped per-device islands, like multiple MPI ranks
    per node).

    quality=True (the search-quality observatory, README "Search-quality
    observatory") appends obs_quality.QUALITY_WIDTH bounded int32
    columns per island to the COMPRESSED telemetry leaf (a `full`
    trace upgrades to `deltas` packing — effective_trace_mode; the
    emitted record stream is unchanged, the established trace-mode
    contract): operator efficacy counters from every generation
    (ga.generation with_quality), migration gain from every ring
    exchange (_migrate return_gain), and end-of-dispatch diversity
    moments + the Hamming sample (_div_stats). All reductions are
    on-device and collective-free; the fetch stays ONE leaf.
    """
    if n_islands is None:
        n_islands = mesh.devices.size
    L = local_islands(mesh, n_islands)
    pop = cfg.pop_size

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(),
                  ga.PopState(slots=P(AXIS), rooms=P(AXIS), penalty=P(AXIS),
                              hcv=P(AXIS), scv=P(AXIS))),
        out_specs=(ga.PopState(slots=P(AXIS), rooms=P(AXIS),
                               penalty=P(AXIS), hcv=P(AXIS), scv=P(AXIS)),
                   P(AXIS), P()),
        check_vma=False)
    def _run(pa, key, state):
        my_key = jax.random.fold_in(key, lax.axis_index(AXIS))
        q0 = jnp.zeros((L, obs_quality.N_OPS), jnp.int32)
        mg0 = jnp.zeros((L,), jnp.int32)

        def epoch(carry, k):
            st, q, mg = carry

            def gen_step(s, kk):
                sb = _blocks(s, L, pop)
                kks = jax.random.split(kk, L)
                if quality:
                    sb, qg = jax.vmap(
                        lambda b, kb: ga.generation(
                            pa, kb, b, cfg, with_quality=True))(sb, kks)
                else:
                    sb = jax.vmap(
                        lambda b, kb: ga.generation(pa, kb, b,
                                                    cfg))(sb, kks)
                    qg = q0
                # each island is penalty-sorted, so row 0 is its best
                tr = jnp.stack([sb.hcv[:, 0], sb.scv[:, 0]], axis=-1)
                return _flat(sb), (tr, qg)        # tr: (L, 2)
            gen_keys = jax.random.split(k, gens_per_epoch)
            st, (tr, qgs) = lax.scan(gen_step, st, gen_keys)
            if quality:
                q = q + jnp.sum(qgs, axis=0)
                st, g = _migrate(st, n_islands, L, return_gain=True)
                mg = mg + g
            else:
                st = _migrate(st, n_islands, L)
            return (st, q, mg), tr                # tr: (gens, L, 2)

        epoch_keys = jax.random.split(my_key, n_epochs)
        (state, qops, mig), trace = lax.scan(epoch, (state, q0, mg0),
                                             epoch_keys)
        # (n_epochs, gens, L, 2) -> (L, n_epochs, gens, 2): concat over
        # devices then yields island-major (n_islands, n_epochs, gens, 2)
        trace = jnp.transpose(trace, (2, 0, 1, 3))
        if quality:
            trace = _compress_trace(
                trace.reshape(L, n_epochs * gens_per_epoch, 2), None,
                effective_trace_mode(trace_mode, True),
                cap=(n_epochs * gens_per_epoch
                     if trace_mode == "full" else None))
            trace = _append_quality(
                trace, qops, mig, _div_rows(pa, _blocks(state, L, pop)))
        elif trace_mode != "full":
            trace = _compress_trace(
                trace.reshape(L, n_epochs * gens_per_epoch, 2), None,
                trace_mode)
        best_local = jnp.min(_blocks(state, L, pop).penalty[:, 0])
        global_best = lax.pmin(best_local, AXIS)
        return state, trace, global_best

    return _donate(_run, donate, 2,
                   name=(f"isl_run_e{n_epochs}x{gens_per_epoch}"
                         f"_{trace_mode}" + ("_q" if quality else "")))


# Python int, NOT a jnp scalar: a module-level device array would
# initialize the default backend at import time, silently defeating the
# engine's later jax_platforms switch (backend="cpu")
_SENTINEL = 2 ** 31 - 1

# --- device-side telemetry reduction (tt-obs; ROADMAP dispatch-pipeline
# follow-up, EvoX-style streaming stats — PAPERS.md arXiv:2301.12457 /
# 2405.03605). The runners' per-GENERATION (hcv, scv) best trace is the
# biggest leaf the host fetches every dispatch: n_islands x n_epochs x
# gens x 2 int32, growing linearly with fused-dispatch depth. But the
# logEntry protocol only ever EMITS the strict-improvement subsequence
# of that trace, and every control read (phase switch, kick,
# checkpoint best fold) only needs its minimum — so `deltas` mode
# compresses the trace ON DEVICE to the dispatch-local improvement
# events (gen index, hcv, scv), and `stats` mode adds streamed moments
# (mean/var/min/max of the per-generation best) while still shipping
# the same events. The emitted record stream is IDENTICAL to full mode
# (tests/test_obs.py pins it): an emitted generation is by definition a
# dispatch-local improvement, and the host re-applies its exact
# emission floor over the shipped events.

# Improvement-event capacity per island per dispatch. Overflow (more
# strict improvements than slots — only plausible in a first dispatch
# at very long fusion) drops the tail on device; the shipped count
# exposes it and the engine warns + counts it (obs metric
# `engine.trace_delta_overflow`) instead of silently under-reporting.
TRACE_DELTAS_CAP = int(os.environ.get("TT_TRACE_DELTAS_CAP", "64"))

TRACE_MODES = ("full", "deltas", "stats")

# moments shipped in stats mode (float32, bitcast through the int32
# telemetry leaf): mean/var/min/max of the per-generation best's
# reported value across the dispatch
TRACE_N_MOMENTS = 4


def _reported_f32(hcv, scv):
    """The protocol's reported value as one float32 scalar per entry:
    scv alone once feasible, else hcv*1e6 + scv — the lex order
    flattened onto a single axis so streamed moments can average it
    (jsonl.reported_best is the int-domain twin)."""
    return jnp.where(hcv == 0, scv.astype(jnp.float32),
                     hcv.astype(jnp.float32) * 1e6
                     + scv.astype(jnp.float32))


def _moment_rows(rep, axis=None, where=None):
    """TRACE_N_MOMENTS bitcast-int32 rows of (mean, var, min, max) of
    `rep` over `axis` — THE stats-mode moment layout every consumer
    decodes (engine reads them back with `.view(np.float32)`). `where`
    selects the mask-weighted variant (the compressed-trace leaf's
    valid-generation mask); var is clamped at 0 against fp cancellation
    either way."""
    if where is None:
        mean = jnp.mean(rep, axis=axis)
        var = jnp.maximum(jnp.mean(rep * rep, axis=axis) - mean * mean,
                          0.0)
        mn = jnp.min(rep, axis=axis)
        mx = jnp.max(rep, axis=axis)
    else:
        w = where.astype(jnp.float32)
        n = jnp.maximum(jnp.sum(w, axis=axis), 1.0)
        mean = jnp.sum(rep * w, axis=axis) / n
        var = jnp.maximum(jnp.sum(rep * rep * w, axis=axis) / n
                          - mean * mean, 0.0)
        mn = jnp.min(jnp.where(where, rep, jnp.inf), axis=axis)
        mx = jnp.max(jnp.where(where, rep, -jnp.inf), axis=axis)
    return lax.bitcast_convert_type(jnp.stack([mean, var, mn, mx]),
                                    jnp.int32)


def _reported_i32(hcv, scv):
    """jsonl.reported_best on device, int32: scv when feasible, else
    hcv*1e6+scv — the quality observatory's migration-gain domain.
    (Overflows past hcv ~2147, far beyond any real instance's hcv.)"""
    return jnp.where(hcv == 0, scv,
                     hcv * jnp.int32(1_000_000) + scv).astype(jnp.int32)


def _hamming_stride(pop: int) -> int:
    """Static coprime pair stride for the diversity Hamming sample:
    the largest a <= pop//2 with gcd(a, pop) == 1, so pairing row i
    with row (i + a) mod pop walks a full cycle with maximal spread —
    a DETERMINISTIC sample, deliberately not jax.random.permutation
    (whose shuffle-sort under shard_map is exactly the TT302 collective
    hazard the telemetry path must never introduce). 0 when pop < 2
    (no pairs exist)."""
    if pop < 2:
        return 0
    for a in range(max(1, pop // 2), 0, -1):
        if math.gcd(a, pop) == 1:
            return a
    return 1


@obs_prof.scope("tt.quality")
def _div_stats(event_mask, slots, pen, scv):
    """One island's diversity block: (obs_quality.N_DIV,) bitcast-int32
    of penalty mean/var/min/max, scv mean/var/min/max, and the bounded
    coprime-stride Hamming sample mean over slot assignments — the
    fraction of differing LIVE slots averaged over min(pop,
    HAMMING_PAIRS) stride-paired individuals (padded events masked
    out). Everything is elementwise + local reductions: no collectives,
    no RNG (tt-analyze TT604 / TT302 discipline).

    Moments are computed on MIN-SHIFTED values (x - min(x)): the
    infeasible penalty domain sits at ~1e6, where float32's
    mean-of-squares loses the whole population spread to cancellation
    (a measured var of 0.0 across a visibly spread population) — the
    shift keeps the squares at spread scale. mean = min + mean(shift);
    min/max are exact either way."""

    def moments(x):
        mn = jnp.min(x)
        c = x - mn
        mean_c = jnp.mean(c)
        var = jnp.maximum(jnp.mean(c * c) - mean_c * mean_c, 0.0)
        return lax.bitcast_convert_type(
            jnp.stack([mn + mean_c, var, mn, jnp.max(x)]), jnp.int32)

    parts = [moments(pen.astype(jnp.float32)),
             moments(scv.astype(jnp.float32))]
    pop = slots.shape[0]
    k_pairs = min(pop, obs_quality.HAMMING_PAIRS)
    stride = _hamming_stride(pop)
    if stride == 0:
        ham = jnp.zeros((1,), jnp.float32)
    else:
        a = slots[:k_pairs]
        b = jnp.roll(slots, -stride, axis=0)[:k_pairs]
        m = event_mask.astype(jnp.float32)
        live = jnp.maximum(jnp.sum(m), 1.0)
        ham = (jnp.sum((a != b).astype(jnp.float32) * m[None, :])
               / (k_pairs * live))[None]
    parts.append(lax.bitcast_convert_type(ham, jnp.int32))
    return jnp.concatenate(parts)


def _div_rows(pa, blk: ga.PopState):
    """(L, obs_quality.N_DIV) diversity rows over per-island blocks
    (one shared problem; the lane runner vmaps _div_stats with its
    per-lane masks instead)."""
    return jax.vmap(lambda s, p, v: _div_stats(pa.event_mask, s, p, v))(
        blk.slots, blk.penalty, blk.scv)


def _append_quality(trace, qops, mig, div):
    """THE quality-block wire layout: [event leaf | N_OPS operator
    counters | migration gain | N_DIV diversity]. obs_quality's OFF_*
    constants and split_quality decode exactly this column order, so
    the three runners (static/dynamic/lane) share this one packer —
    a column added in one place but not the others would otherwise
    mis-decode silently (int32 counters bitcast as float32 diversity),
    since decode_rows validates only the total width."""
    return jnp.concatenate([trace, qops, mig[:, None], div], axis=1)


def effective_trace_mode(trace_mode: str, quality: bool) -> str:
    """The telemetry leaf's actual packing. The quality block rides the
    COMPRESSED leaf (extra bounded int32 columns — the fetch stays one
    leaf), so quality mode upgrades a `full` trace to `deltas` packing.
    The emitted record stream is unchanged by that upgrade: full and
    compressed leaves already yield identical streams (the established
    trace-mode contract, tests/test_obs.py)."""
    if quality and trace_mode == "full":
        return "deltas"
    return trace_mode


def split_quality(trace, quality: bool):
    """Host-side split of a fetched telemetry leaf into (event leaf,
    quality block) — numpy only. The quality block is the trailing
    obs_quality.QUALITY_WIDTH columns the runners appended; the event
    leaf keeps the exact layout trace_events expects."""
    if not quality:
        return trace, None
    tr = np.asarray(trace)
    w = obs_quality.QUALITY_WIDTH
    return tr[:, :-w], tr[:, -w:]


def trace_leaf_width(n_gens: int, trace_mode: str,
                     quality: bool = False) -> int:
    """Packed telemetry columns per island for a compressed trace:
    K events x (gen, hcv, scv) + the improvement count [+ moments]
    [+ the quality observatory's block]. A quality-upgraded `full`
    trace is uncapped (K = n_gens; see _compress_trace's `cap`)."""
    if quality and trace_mode == "full":
        k = n_gens
    else:
        k = min(n_gens, TRACE_DELTAS_CAP)
    mode = effective_trace_mode(trace_mode, quality)
    return (3 * k + 1 + (TRACE_N_MOMENTS if mode == "stats" else 0)
            + (obs_quality.QUALITY_WIDTH if quality else 0))


def _compress_trace(trace, n_valid, trace_mode: str, cap: int = None):
    """(L, T, 2) per-generation (hcv, scv) trace -> (L, W) packed int32.

    Per island: a scan computes the dispatch-local running lex-min of
    (hcv, scv) — lex order equals reported-value order under the
    protocol's own scv < 1e6 packing assumption (jsonl.reported_best) —
    and marks the strict improvements; a cumsum-indexed scatter packs
    the LAST K improvement rows (gen, hcv, scv) into a sentinel-padded
    (K, 3) block (overflow rows land in a discarded K+1th slot). On
    overflow the EARLIEST events are the ones dropped — each is
    superseded by a later shipped event, so the dispatch's final best
    (the value control reads: best_seen, the post-feasibility switch)
    always survives; dropping the tail instead would lose exactly the
    best values. The improvement count rides along so the host can
    detect overflow.
    `n_valid` masks trailing sentinel rows of a dynamic-gens trace —
    None (every row real), a scalar (the dynamic runner's shared
    n_gens), or an (L,) vector (the lane runner's per-lane quantum
    counts). Stats mode appends bitcast float32 moments over the valid
    rows.

    `cap` overrides TRACE_DELTAS_CAP for the event-slot count. The
    quality runners pass cap=T when UPGRADING a `full` trace
    (effective_trace_mode): a user who chose full asked for every
    generation, so the upgraded leaf must keep every improvement —
    n_imp <= T == K means overflow is impossible there, and the
    quality-on stream stays identical to the quality-off full stream
    unconditionally (not just under the cap). A user-chosen
    deltas/stats mode keeps its configured cap semantics."""
    T = trace.shape[1]
    K = min(T, TRACE_DELTAS_CAP if cap is None else cap)
    gidx = jnp.arange(T, dtype=jnp.int32)
    if n_valid is None:
        nv = jnp.full((trace.shape[0],), T, jnp.int32)
    else:
        nv = jnp.broadcast_to(jnp.asarray(n_valid, jnp.int32),
                              (trace.shape[0],))

    def one(tr, n_val):
        valid = gidx < n_val
        h, s = tr[:, 0], tr[:, 1]

        def step(carry, x):
            bh, bs = carry
            hh, ss, ok = x
            imp = ok & ((hh < bh) | ((hh == bh) & (ss < bs)))
            return ((jnp.where(imp, hh, bh), jnp.where(imp, ss, bs)),
                    imp)

        _, mask = lax.scan(
            step, (jnp.int32(_SENTINEL), jnp.int32(_SENTINEL)),
            (h, s, valid))
        n_imp = jnp.sum(mask.astype(jnp.int32))
        pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
        # keep the last K: slot < K is guaranteed (pos <= n_imp - 1)
        slot = pos - jnp.maximum(n_imp - K, 0)
        idx = jnp.where(mask & (slot >= 0), slot, K)
        rows = jnp.stack([gidx, h, s], axis=1)
        ev = jnp.full((K + 1, 3), _SENTINEL, jnp.int32).at[idx].set(rows)
        parts = [ev[:K].reshape(-1), n_imp[None]]
        if trace_mode == "stats":
            parts.append(_moment_rows(_reported_f32(h, s), where=valid))
        return jnp.concatenate(parts)

    return jax.vmap(one)(trace, nv)


def trace_events(trace, trace_mode: str):
    """HOST-side decode of one fetched telemetry leaf.

    Returns `(events, counts, moments)` where `events[i]` is island i's
    ordered `(gen, hcv, scv)` candidate list, `counts` the on-device
    improvement counts (None in full mode — every row ships), and
    `moments` an (n_islands, 4) float32 `[mean, var, min, max]` array
    (stats mode only). The emitters apply their own best/emitted floors
    over the events, so full and compressed leaves yield the SAME
    record stream: full mode lists every generation and the floor
    selects the improvements; deltas/stats ship the improvements
    pre-selected (gen indices ride along) and the floor is a no-op on
    everything the full path would also have skipped.

    Accepts the full trace at any of its shapes ((n_islands, E, G, 2)
    static, (n_islands, 1, G, 2) dynamic post-slice) and the packed
    (n_islands, W) int32 leaf — the layouts are unambiguous by ndim.
    Sentinel rows (a dynamic tail's unexecuted generations, unused
    event slots) are dropped; numpy only, no device access."""
    tr = np.asarray(trace)
    if tr.ndim != 2:               # full per-generation trace
        flat = tr.reshape(tr.shape[0], -1, 2)
        events = [[(g, int(row[0]), int(row[1]))
                   for g, row in enumerate(isl) if row[0] != _SENTINEL]
                  for isl in flat]
        return events, None, None
    n_isl, W = tr.shape
    n_mom = TRACE_N_MOMENTS if trace_mode == "stats" else 0
    K = (W - 1 - n_mom) // 3
    ev = tr[:, :3 * K].reshape(n_isl, K, 3)
    counts = tr[:, 3 * K].copy()
    moments = None
    if n_mom:
        moments = np.ascontiguousarray(
            tr[:, 3 * K + 1:]).view(np.float32)
    events = [[(int(g), int(h), int(s)) for g, h, s in isl
               if g != _SENTINEL] for isl in ev]
    return events, counts, moments


def make_polish_runner(mesh: Mesh, cfg: ga.GAConfig,
                       n_islands: int = None, donate: bool = False,
                       with_passes: bool = False):
    """Initial-population LS polish as its own dispatchable program:
    `polish(pa, key, state, n_sweeps) -> state` runs up to `n_sweeps`
    (a RUNTIME argument) convergence-bounded sweep passes on every
    island's population and re-evaluates.

    The reference LS-polishes its initial population before generation 0
    (ga.cpp:429-434) with the clock checked inside the loop
    (Solution.cpp:499); fusing that polish into one init dispatch made
    it unboundable — a 30-pass converge polish at comp scale can eat a
    whole 60 s budget in one dispatch. Chunked dispatches of a few
    passes each give the engine clock checks between chunks, and the
    runtime sweep count means one compile serves every chunk size.

    Returns `(state, stats)` where stats = stacked (penalty, hcv, scv)
    as one (3, n_islands*pop) int32 array — the engine's between-chunk
    bookkeeping (stall detection + logEntry emission) then costs ONE
    host fetch per chunk instead of three (each fetch is a multi-second
    round trip on tunneled devices; VERDICT round-3 weak #3).

    with_passes=True (tt-obs `--trace-mode stats`) appends one extra
    stats ROW carrying each device's executed sweep-pass count
    (sweep_local_search return_passes), then TRACE_N_MOMENTS rows of
    bitcast float32 moments (mean/var/min/max of the polished
    population's reported values across the device's shard rows) — the
    polish/tail-polish endgame ships the same streamed-moment telemetry
    as the stats-mode generation runners, on the same single fetch. The
    trajectory is untouched — the determinism A/Bs across trace modes
    depend on that."""
    L = local_islands(mesh, n_islands)
    pop = cfg.pop_size

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(),
                  ga.PopState(slots=P(AXIS), rooms=P(AXIS), penalty=P(AXIS),
                              hcv=P(AXIS), scv=P(AXIS)), P()),
        out_specs=(ga.PopState(slots=P(AXIS), rooms=P(AXIS), penalty=P(AXIS),
                               hcv=P(AXIS), scv=P(AXIS)), P(None, AXIS)),
        check_vma=False)
    @obs_prof.scope("tt.polish")
    def _polish(pa, key, state, n_sweeps):
        from timetabling_ga_tpu.ops.sweep import sweep_local_search
        my_key = jax.random.fold_in(key, lax.axis_index(AXIS))
        # the sweep LS is per-individual, so it runs on the flat shard;
        # only the sort inside evaluate is per-island
        out = sweep_local_search(
            pa, my_key, state.slots, state.rooms, n_sweeps=n_sweeps,
            swap_block=cfg.ls_swap_block, converge=True,
            block_events=cfg.ls_block_events, sideways=cfg.ls_sideways,
            hot_k=cfg.ls_hot_k, p3=cfg.p3, return_passes=with_passes)
        slots, rooms = out[0], out[1]
        sb = _blocks(ga.PopState(slots, rooms, state.penalty, state.hcv,
                                 state.scv), L, pop)
        st = _flat(jax.vmap(
            lambda b: ga.evaluate(pa, b.slots, b.rooms))(sb))
        stats = jnp.stack([st.penalty, st.hcv, st.scv])
        if with_passes:
            # extra stats ROWS, broadcast across the device's columns:
            # rows are the unsharded axis, so the global array stays a
            # clean (3+1+4, n_islands*pop) — the host reads row 3
            # (pass count) and rows 4.. (bitcast float32 moments of the
            # polished population's reported values) and slices them
            # off before its (3, ...) protocol reshape
            cols = stats.shape[1]
            stats = jnp.concatenate(
                [stats, jnp.full((1, cols), out[2], jnp.int32)], axis=0)
            mom = _moment_rows(_reported_f32(st.hcv, st.scv))
            stats = jnp.concatenate(
                [stats, jnp.broadcast_to(mom[:, None],
                                         (TRACE_N_MOMENTS, cols))],
                axis=0)
        return st, stats

    return _donate(_polish, donate, 2,
                   name="polish" + ("_wp" if with_passes else ""))


# Hard bound on the kick's runtime perturbation depth (the scan length
# the compiled program unrolls over). The engine's escalation ladder
# caps at this SAME constant — a deeper request would be silently
# mask-truncated while the trace logged the requested depth.
KICK_MAX_MOVES = 16


def make_kick_runner(mesh: Mesh, cfg: ga.GAConfig,
                     max_moves: int = KICK_MAX_MOVES,
                     n_islands: int = None, donate: bool = False):
    """Stall-kick: reseed the worst half of every island's population
    from mutated copies of its best individual (VERDICT round-4 next #5).

    The reference's escape hatch from a stalled population is migration —
    immigrants overwrite the two worst rows (ga.cpp:522-535) — but a
    single-island run has no migration, and the round-4 race left small
    seed 43 pinned on an scv plateau for its whole budget. The kick is
    the single-island analogue: rows [P/2, P) become copies of row 0
    with `n_moves` random moves applied each (diversity seeded FROM the
    elite, not from scratch — a restart would forfeit the repair work).
    The elite half is untouched, so the island's best never regresses.

    `n_moves` is a RUNTIME argument (<= max_moves, one compile serves
    every depth): repeated stalls let the engine ESCALATE the
    perturbation depth, walking progressively further out of the basin
    the deep-sweep polish keeps re-converging into.

    Returns `kick(pa, key, state, n_moves) -> state` (jitted;
    populations of size < 2 are returned unchanged)."""
    L = local_islands(mesh, n_islands)
    pop = cfg.pop_size
    half = pop // 2

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(),
                  ga.PopState(slots=P(AXIS), rooms=P(AXIS), penalty=P(AXIS),
                              hcv=P(AXIS), scv=P(AXIS)), P()),
        out_specs=ga.PopState(slots=P(AXIS), rooms=P(AXIS), penalty=P(AXIS),
                              hcv=P(AXIS), scv=P(AXIS)),
        check_vma=False)
    def _kick(pa, key, state, n_moves):
        if half < 1:
            return state
        from timetabling_ga_tpu.ops.moves import random_move
        my_key = jax.random.fold_in(key, lax.axis_index(AXIS))

        def kick_island(b, k):
            def clone(kc):
                def body(carry, xs):
                    i, kk = xs
                    s, r = carry
                    s2, r2 = random_move(pa, kk, s, r, cfg.p1, cfg.p2,
                                         cfg.p3)
                    keep = i < n_moves
                    return (jnp.where(keep, s2, s),
                            jnp.where(keep, r2, r)), None
                (s, r), _ = lax.scan(
                    body, (b.slots[0], b.rooms[0]),
                    (jnp.arange(max_moves), jax.random.split(kc, max_moves)))
                return s, r

            cs, cr = jax.vmap(clone)(jax.random.split(k, pop - half))
            slots = b.slots.at[half:].set(cs)
            rooms = b.rooms.at[half:].set(cr)
            return ga.evaluate(pa, slots, rooms)

        sb = _blocks(state, L, pop)
        return _flat(jax.vmap(kick_island)(
            sb, jax.random.split(my_key, L)))

    return _donate(_kick, donate, 2, name=f"kick_m{max_moves}")


def make_shrink_runner(mesh: Mesh, pop_in: int, pop_out: int,
                       n_islands: int = None):
    """Truncate every island's population to its elite `pop_out` rows
    (islands are (penalty, scv)-sorted, so rows [0, pop_out) are the
    best). Used at the post-feasibility phase switch when the endgame
    runs a smaller population than the repair phase (post_pop_size):
    fewer rows per generation buys proportionally more deep-polish
    generations per second, and the discarded rows are the repair
    phase's worst — measured on comp01s to beat polishing the full
    population (BASELINE.md round 5).

    Never donated: the output rows are a strict subset of the input's
    (pop_out < pop_in), so no output buffer matches an input shape and
    XLA would reject every alias with a 'donated buffer not usable'
    warning — donation here is all cost, no reuse."""
    L = local_islands(mesh, n_islands)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(ga.PopState(slots=P(AXIS), rooms=P(AXIS), penalty=P(AXIS),
                              hcv=P(AXIS), scv=P(AXIS)),),
        out_specs=ga.PopState(slots=P(AXIS), rooms=P(AXIS), penalty=P(AXIS),
                              hcv=P(AXIS), scv=P(AXIS)),
        check_vma=False)
    def _shrink(state):
        blk = _blocks(state, L, pop_in)
        return _flat(jax.tree.map(lambda x: x[:, :pop_out], blk))

    return _named_jit(_shrink, name=f"shrink_{pop_in}to{pop_out}")


def _lahc_specs():
    """Sharding spec tree for LahcState: every field has leading axis P
    (walkers), sharded along the island axis."""
    from timetabling_ga_tpu.ops.lahc import LahcState
    from timetabling_ga_tpu.ops.delta import LSState
    return LahcState(
        ls=LSState(*([P(AXIS)] * 7)),
        hist_pen=P(AXIS), hist_scv=P(AXIS), step=P(AXIS),
        best_slots=P(AXIS), best_rooms=P(AXIS),
        best_pen=P(AXIS), best_hcv=P(AXIS), best_scv=P(AXIS))


def make_lahc_runners(mesh: Mesh, cfg: ga.GAConfig, hist_len: int,
                      k_cands: int = 1, n_islands: int = None,
                      donate: bool = False, with_moments: bool = False):
    """Late-Acceptance Hill Climbing endgame programs (ops/lahc.py):

      init(pa, state)              -> lahc_state   (walkers = pop rows)
      run(pa, key, lahc_state, n)  -> (lahc_state, stats)
      finalize(lahc_state)         -> PopState     (best snapshots)

    `n` (steps per dispatch) is a RUNTIME argument — the engine sizes
    each dispatch to its wall-clock budget, like the polish/dynamic
    runners. `stats` is one (3, n_islands) int32 array of each island's
    lex-best walker's best-so-far (pen, hcv, scv) — ONE host fetch per
    chunk for the logEntry stream. `finalize` returns each island's
    best snapshots as a lex-sorted PopState, so the endTry fetch reads
    it exactly like a GA population. Walkers are per-island independent;
    no migration runs during LAHC (each walker is its own chain — the
    diversity is the walker ensemble, seeded from the elite rows).

    with_moments=True (tt-obs `--trace-mode stats`) appends
    TRACE_N_MOMENTS rows of bitcast float32 walker-ensemble moments
    (mean/var/min/max of each island's per-walker best-so-far reported
    values) to the run program's stats — the LAHC endgame ships the
    same streamed-moment telemetry as the stats-mode generation
    runners, on the same single fetch, with the walker trajectory
    untouched (the across-mode determinism A/B pins it)."""
    from timetabling_ga_tpu.ops import lahc as lahc_ops
    L = local_islands(mesh, n_islands)
    pop = cfg.pop_size
    specs = _lahc_specs()
    pop_specs = ga.PopState(slots=P(AXIS), rooms=P(AXIS), penalty=P(AXIS),
                            hcv=P(AXIS), scv=P(AXIS))

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P(), pop_specs),
        out_specs=specs, check_vma=False)
    def _init(pa, state):
        return lahc_ops.init_lahc(pa, state.slots, state.rooms, hist_len)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P(), P(), specs, P()),
        out_specs=(specs, P(None, AXIS)), check_vma=False)
    def _run(pa, key, lstate, n_steps):
        my_key = jax.random.fold_in(key, lax.axis_index(AXIS))
        lstate = lahc_ops.lahc_steps(pa, my_key, lstate, n_steps,
                                     cfg.p1, cfg.p2, cfg.p3, k_cands)
        # per-island lex-best over each island's walker block
        bp = lstate.best_pen.reshape(L, pop)
        bh = lstate.best_hcv.reshape(L, pop)
        bs = lstate.best_scv.reshape(L, pop)
        idx = jax.vmap(lambda p_, s_: fitness.lex_order(p_, s_)[0])(bp, bs)
        la = jnp.arange(L)
        stats = jnp.stack([bp[la, idx], bh[la, idx], bs[la, idx]])
        if with_moments:
            # (L, pop) walker reported values -> (4, L) moment rows
            mom = _moment_rows(_reported_f32(bh, bs), axis=1)
            stats = jnp.concatenate([stats, mom], axis=0)
        return lstate, stats

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(specs,),
        out_specs=pop_specs, check_vma=False)
    def _finalize(lstate):
        def one_island(bs, br, bp, bh, bv):
            order = fitness.lex_order(bp, bv)
            return ga.PopState(slots=bs[order], rooms=br[order],
                               penalty=bp[order], hcv=bh[order],
                               scv=bv[order])
        blk = jax.vmap(one_island)(
            lstate.best_slots.reshape(L, pop, -1),
            lstate.best_rooms.reshape(L, pop, -1),
            lstate.best_pen.reshape(L, pop),
            lstate.best_hcv.reshape(L, pop),
            lstate.best_scv.reshape(L, pop))
        return _flat(blk)

    return (_donate(_init, donate, 1, name=f"lahc_init_h{hist_len}"),
            _donate(_run, donate, 2,
                    name=(f"lahc_run_h{hist_len}_k{k_cands}"
                          + ("_m" if with_moments else ""))),
            _donate(_finalize, donate, 0, name="lahc_fin"))


def make_island_runner_dynamic(mesh: Mesh, cfg: ga.GAConfig,
                               max_gens: int, n_islands: int = None,
                               donate: bool = False,
                               trace_mode: str = "full",
                               quality: bool = False):
    """Like `make_island_runner(n_epochs=1)` but the generation count is
    a RUNTIME argument `n_gens <= max_gens`: `run(pa, key, state, n_gens)`.

    One compilation serves every tail size, so the engine can spend the
    last fraction of a wall-clock budget (-t, Control.cpp:62-68) on a
    right-sized dispatch instead of idling — the reference wastes nothing
    there because it checks its clock before every LS candidate
    (Solution.cpp:499); our granularity is one generation. Trace rows at
    index >= n_gens hold INT_MAX sentinels (the host slices them off).
    Migration still closes the epoch (ga.cpp:522-535 cadence).
    trace_mode "deltas"/"stats" ships the compressed telemetry leaf
    instead (_compress_trace, with rows >= n_gens masked out of the
    moments; sentinel rows can never register as improvements).
    quality=True appends the quality observatory's block exactly like
    make_island_runner's (the executed fori_loop covers only real
    generations, so the operator counters never see sentinel rows).
    """
    if n_islands is None:
        n_islands = mesh.devices.size
    L = local_islands(mesh, n_islands)
    pop = cfg.pop_size

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(),
                  ga.PopState(slots=P(AXIS), rooms=P(AXIS), penalty=P(AXIS),
                              hcv=P(AXIS), scv=P(AXIS)), P()),
        out_specs=(ga.PopState(slots=P(AXIS), rooms=P(AXIS),
                               penalty=P(AXIS), hcv=P(AXIS), scv=P(AXIS)),
                   P(AXIS), P()),
        check_vma=False)
    def _run(pa, key, state, n_gens):
        my_key = jax.random.fold_in(key, lax.axis_index(AXIS))
        tr0 = jnp.full((max_gens, L, 2), _SENTINEL, jnp.int32)
        q0 = jnp.zeros((L, obs_quality.N_OPS), jnp.int32)

        def body(i, carry):
            st, tr, q = carry
            sb = _blocks(st, L, pop)
            kks = jax.random.split(jax.random.fold_in(my_key, i), L)
            if quality:
                sb, qg = jax.vmap(
                    lambda b, kb: ga.generation(
                        pa, kb, b, cfg, with_quality=True))(sb, kks)
                q = q + qg
            else:
                sb = jax.vmap(
                    lambda b, kb: ga.generation(pa, kb, b, cfg))(sb, kks)
            tr = lax.dynamic_update_index_in_dim(
                tr, jnp.stack([sb.hcv[:, 0], sb.scv[:, 0]], axis=-1),
                i, 0)
            return _flat(sb), tr, q

        state, trace, qops = lax.fori_loop(0, n_gens, body,
                                           (state, tr0, q0))
        if quality:
            state, mig = _migrate(state, n_islands, L, return_gain=True)
            trace = _compress_trace(jnp.transpose(trace, (1, 0, 2)),
                                    n_gens,
                                    effective_trace_mode(trace_mode,
                                                         True),
                                    cap=(max_gens if trace_mode ==
                                         "full" else None))
            trace = _append_quality(
                trace, qops, mig, _div_rows(pa, _blocks(state, L, pop)))
        else:
            state = _migrate(state, n_islands, L)
            if trace_mode != "full":
                trace = _compress_trace(jnp.transpose(trace, (1, 0, 2)),
                                        n_gens, trace_mode)
            else:
                # (max_gens, L, 2) -> (L, 1, max_gens, 2): island-major
                # like the static runner's trace
                trace = jnp.transpose(trace, (1, 0, 2))[:, None]
        best_local = jnp.min(_blocks(state, L, pop).penalty[:, 0])
        global_best = lax.pmin(best_local, AXIS)
        return state, trace, global_best

    return _donate(_run, donate, 2,
                   name=(f"isl_rundyn_g{max_gens}_{trace_mode}"
                         + ("_q" if quality else "")))


# ---------------------------------------------------------------------------
# Multi-tenant lane programs (the serve subsystem, timetabling_ga_tpu/serve)
#
# A LANE is one slot of the island axis carrying one JOB's island: the
# scheduler stacks up to n_lanes same-bucket jobs into one dispatch, so
# the whole mesh advances many tenants' populations in a single fused
# program. Differences from the single-problem runners above:
#   - ProblemArrays leaves carry a leading LANE axis (each lane has its
#     own padded instance data — same bucket SHAPE, different values);
#   - per-lane seed/chunk indices derive each lane's RNG stream, so one
#     tenant's draws never depend on who shares the dispatch;
#   - per-lane generation counts (a lane runs min(quantum, remaining));
#   - NO migration and NO cross-lane collectives: lanes are different
#     problems, and solutions must never mix. The compiled program is
#     collective-free, so per-device trip-count divergence is harmless.


def make_lane_init(mesh: Mesh, pop_size: int, cfg: ga.GAConfig,
                   n_lanes: int):
    """Per-lane population init: `init(pa_l, seeds) -> PopState` where
    every ProblemArrays leaf of `pa_l` has a leading (n_lanes,) axis and
    `seeds` is (n_lanes,) int32. Lane i draws from key(seeds[i]) only —
    job identity, not lane position, determines the stream, so a job
    resumed into a different lane reproduces the same evolution."""
    L = local_islands(mesh, n_lanes)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS)),
        out_specs=ga.PopState(slots=P(AXIS), rooms=P(AXIS),
                              penalty=P(AXIS), hcv=P(AXIS), scv=P(AXIS)),
        check_vma=False)
    def _init(pa_l, seeds):
        st = jax.vmap(
            lambda pa_i, seed: ga.init_population(
                pa_i, jax.random.key(seed), pop_size, cfg))(pa_l, seeds)
        return _flat(st)

    def run(pa_l, seeds):
        _mark_trace("lane_init")
        return _init(pa_l, seeds)

    return _named_jit(run, name=f"lane_init_p{pop_size}_l{n_lanes}")


def make_lane_runner(mesh: Mesh, cfg: ga.GAConfig, max_gens: int,
                     n_lanes: int, donate: bool = False,
                     trace_mode: str = "full", quality: bool = False):
    """The serve dispatch program:
    `run(pa_l, seeds, chunks, state, gens) -> (state, trace)`.

      pa_l    ProblemArrays, every leaf with leading (n_lanes,) axis
      seeds   (n_lanes,) int32 — per-job RNG identity
      chunks  (n_lanes,) int32 — per-job dispatch counter: chunk c of a
              job folds (seed, c), so a job's stream is a pure function
              of its own progress, independent of lane packing and of
              whatever other jobs ran in the same dispatches
      state   global PopState, (n_lanes * pop, E) leaves, lane-sharded
      gens    (n_lanes,) int32 — generations to run this quantum
              (0 for idle/filler lanes; <= max_gens)
      trace   (n_lanes, max_gens, 2) int32 per-generation (hcv, scv) of
              each lane's best row; rows >= gens hold INT_MAX sentinels.
              trace_mode "deltas"/"stats" ships the packed (n_lanes,
              trace_leaf_width(max_gens, mode)) leaf instead
              (_compress_trace, per-lane gens as the valid mask) — the
              serve path's telemetry shrinks exactly like the engine's.
              quality=True appends each lane's quality block (operator
              counters masked to the lane's own executed generations;
              migration gain 0 — lanes never migrate; diversity from
              the lane's final population under its OWN event mask)

    One compile serves every quantum size and every job mix of a
    bucket. Each device iterates to the max of ITS lanes' counts and
    masks per-lane updates beyond a lane's own count."""
    L = local_islands(mesh, n_lanes)
    pop = cfg.pop_size

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS),
                  ga.PopState(slots=P(AXIS), rooms=P(AXIS), penalty=P(AXIS),
                              hcv=P(AXIS), scv=P(AXIS)), P(AXIS)),
        out_specs=(ga.PopState(slots=P(AXIS), rooms=P(AXIS),
                               penalty=P(AXIS), hcv=P(AXIS), scv=P(AXIS)),
                   P(AXIS)),
        check_vma=False)
    def _run(pa_l, seeds, chunks, state, gens):
        sb = _blocks(state, L, pop)
        tr0 = jnp.full((L, max_gens, 2), _SENTINEL, jnp.int32)
        q0 = jnp.zeros((L, obs_quality.N_OPS), jnp.int32)
        n_steps = jnp.max(gens)

        def lane_keys(seed, chunk):
            return jax.random.fold_in(jax.random.key(seed), chunk)

        keys = jax.vmap(lane_keys)(seeds, chunks)

        def body(i, carry):
            st, tr, q = carry

            def one_lane(pa_i, k, b, g, tr_i, q_i):
                if quality:
                    b2, qg = ga.generation(pa_i, jax.random.fold_in(k, i),
                                           b, cfg, with_quality=True)
                else:
                    b2 = ga.generation(pa_i, jax.random.fold_in(k, i), b,
                                       cfg)
                    qg = jnp.zeros((obs_quality.N_OPS,), jnp.int32)
                keep = i < g
                b = jax.tree.map(
                    lambda new, old: jnp.where(keep, new, old), b2, b)
                # a masked (not-executed) generation must not count:
                # the lane's stream is a pure function of its own
                # progress, and so are its quality counters
                q_i = q_i + jnp.where(keep, qg, 0)
                row = jnp.stack([b.hcv[0], b.scv[0]])
                tr_i = lax.dynamic_update_index_in_dim(
                    tr_i, jnp.where(keep, row, tr_i[i]), i, 0)
                return b, tr_i, q_i

            st, tr, q = jax.vmap(one_lane)(pa_l, keys, st, gens, tr, q)
            return st, tr, q

        sb, trace, qops = lax.fori_loop(0, n_steps, body, (sb, tr0, q0))
        if quality:
            trace = _compress_trace(trace, gens,
                                    effective_trace_mode(trace_mode,
                                                         True),
                                    cap=(max_gens if trace_mode ==
                                         "full" else None))
            div = jax.vmap(
                lambda pa_i, s, p, v: _div_stats(pa_i.event_mask, s, p,
                                                 v))(
                pa_l, sb.slots, sb.penalty, sb.scv)
            # lanes never migrate: the gain column ships zeros so the
            # layout stays uniform with the island runners'
            trace = _append_quality(trace, qops,
                                    jnp.zeros((L,), jnp.int32), div)
        elif trace_mode != "full":
            trace = _compress_trace(trace, gens, trace_mode)
        return _flat(sb), trace

    def run(pa_l, seeds, chunks, state, gens):
        _mark_trace("lane_runner")
        return _run(pa_l, seeds, chunks, state, gens)

    return _donate(run, donate, 3,
                   name=(f"lane_run_g{max_gens}_l{n_lanes}_{trace_mode}"
                         + ("_q" if quality else "")))
