"""Island-model GA over a TPU device mesh.

TPU-native replacement for the reference's MPI island model
(ga.cpp:370-541). The mapping, per SURVEY C15/C17 and section 5:

  MPI rank / island            -> shard of the population along mesh axis
                                  "island" (`shard_map` over a 1-D Mesh)
  MPI_Bcast of the problem     -> replicated ProblemArrays (device_put)
  per-rank seed arithmetic     -> `jax.random.fold_in(key, island_index)`
                                  (replaces `abs(seed+i*(seed/10))`,
                                  ga.cpp:412)
  MPI_Sendrecv ring migration  -> `lax.ppermute`: best solution forward
                                  (tag 2, ga.cpp:522-526), second-best
                                  backward (tag 4, ga.cpp:530-533)
  immigrants replace 2 worst   -> scatter into the sorted population's
                                  last two rows (ga.cpp:344-346, 528, 535)
  MPI_Allreduce(MIN)           -> `lax.pmin` (ga.cpp:237, 248)
  MPI_Barrier pairs            -> none needed; collective semantics
                                  synchronize (SURVEY section 5)

The reference migrates when a per-thread counter hits 100 local periods
(offset 50), making wall-clock cadence depend on thread count — a
scheduling quirk, not a capability (SURVEY section 3.5). Here the cadence
is explicit: `gens_per_epoch` generations between migrations.

Multi-host scaling: the same `Mesh` spans hosts under `jax.distributed`
(ICI within a slice, DCN across slices) with no code change — the mesh
axis is the single abstraction, exactly as the scaling-book recipe
prescribes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from timetabling_ga_tpu.ops import fitness, ga


AXIS = "island"


def make_mesh(n_islands: int = None, devices=None) -> Mesh:
    """1-D device mesh with axis "island" (the reference's MPI_Comm_size
    world, ga.cpp:379)."""
    if devices is None:
        devices = jax.devices()
    if n_islands is not None:
        devices = devices[:n_islands]
    import numpy as np
    return Mesh(np.array(devices), (AXIS,))


def init_island_population(pa, key, mesh: Mesh, pop_size: int,
                           cfg: ga.GAConfig = None) -> ga.PopState:
    """Initialize every island's population directly on its own device.

    Global state shape is (n_islands * pop_size, E) sharded along axis 0;
    each island draws from `fold_in(key, island_index)` so populations are
    independent (divergence from the reference's broadcast-identical
    initial populations, ga.cpp:429-444; SURVEY C17). When
    `cfg.init_sweeps > 0` the initial populations are sweep-LS-polished
    on-device (the reference's initial localSearch, ga.cpp:429-434)."""
    n_islands = mesh.devices.size

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P()),
        out_specs=ga.PopState(slots=P(AXIS), rooms=P(AXIS),
                              penalty=P(AXIS), hcv=P(AXIS), scv=P(AXIS)),
        # check_vma=False: the varying-manual-axes checker rejects
        # lax.switch/scan carries whose tags mix island-varying keys with
        # invariant constants (JAX suggests this workaround in the error).
        check_vma=False)
    def _init(pa_, key_):
        k = jax.random.fold_in(key_, lax.axis_index(AXIS))
        return ga.init_population(pa_, k, pop_size, cfg)

    return _init(pa, key)


def _migrate(state: ga.PopState, n_islands: int) -> ga.PopState:
    """Bidirectional ring migration of 1 migrant each way.

    Best solution to the next island, second-best to the previous
    (ga.cpp:522-535); immigrants overwrite the two worst rows
    (ga.cpp:528, 535, deserialize target ga.cpp:344-346). The population
    is penalty-sorted (best first), so rows 0/1 are the emigrants and
    rows -1/-2 the victims.

    Populations smaller than 3 skip migration entirely: with P <= 2 a
    victim row aliases the BEST row (at P == 1 both writes land on the
    island's only individual; at P == 2 the backward immigrant lands on
    row 0), so migration would destroy the island's best (ADVICE round
    3). At P == 3 row 1 is both an emigrant and a victim, but emigrants
    are snapshotted before the writes and rows 1-2 really are the two
    worst of three — the reference's own semantics for that size
    (ga.cpp:344-346) — so P == 3 migrates normally. The reference
    itself never goes below popSize 10 (ga.cpp:64). The native twin
    (tt_cpu --islands) applies the same P >= 3 guard."""
    if state.penalty.shape[0] < 3:
        return state
    fwd = [(i, (i + 1) % n_islands) for i in range(n_islands)]
    bwd = [(i, (i - 1) % n_islands) for i in range(n_islands)]

    row0 = jax.tree.map(lambda x: x[0], state)
    row1 = jax.tree.map(lambda x: x[1], state)
    imm_f = jax.tree.map(lambda x: lax.ppermute(x, AXIS, fwd), row0)
    imm_b = jax.tree.map(lambda x: lax.ppermute(x, AXIS, bwd), row1)

    state = jax.tree.map(lambda x, a, b: x.at[-1].set(a).at[-2].set(b),
                         state, imm_f, imm_b)
    # restore sorted order (replacement + sort, ga.cpp:580-585), by the
    # reported-metric order (penalty, scv) like everywhere else
    order = fitness.lex_order(state.penalty, state.scv)
    return jax.tree.map(lambda x: x[order], state)


def make_island_runner(mesh: Mesh, cfg: ga.GAConfig, n_epochs: int,
                       gens_per_epoch: int):
    """Build the jitted multi-island evolution step.

    Returns `run(pa, key, state) -> (state, best_trace, global_best)`:
      - state: global PopState sharded over the mesh
      - best_trace: (n_islands, n_epochs, gens_per_epoch, 2) int32 —
        per-GENERATION (hcv, scv) of each island's best individual,
        tracked on-device inside the scan so mid-epoch improvements are
        visible to the JSONL logEntry protocol (ga.cpp:203-228) without
        any per-epoch host fetch; the host reads the whole trace once per
        dispatch
      - global_best: scalar = pmin over islands of the final best penalty
        (the reference's MPI_Allreduce MIN, ga.cpp:237)
    One dispatch runs n_epochs x gens_per_epoch generations on all islands
    including all migrations.
    """
    n_islands = mesh.devices.size

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(),
                  ga.PopState(slots=P(AXIS), rooms=P(AXIS), penalty=P(AXIS),
                              hcv=P(AXIS), scv=P(AXIS))),
        out_specs=(ga.PopState(slots=P(AXIS), rooms=P(AXIS),
                               penalty=P(AXIS), hcv=P(AXIS), scv=P(AXIS)),
                   P(AXIS), P()),
        check_vma=False)
    def _run(pa, key, state):
        my_key = jax.random.fold_in(key, lax.axis_index(AXIS))

        def epoch(st, k):
            def gen_step(s, kk):
                s = ga.generation(pa, kk, s, cfg)
                # population is penalty-sorted, so row 0 is the best
                return s, jnp.stack([s.hcv[0], s.scv[0]])
            gen_keys = jax.random.split(k, gens_per_epoch)
            st, tr = lax.scan(gen_step, st, gen_keys)     # (gens, 2)
            st = _migrate(st, n_islands)
            return st, tr

        epoch_keys = jax.random.split(my_key, n_epochs)
        state, trace = lax.scan(epoch, state, epoch_keys)
        global_best = lax.pmin(state.penalty[0], AXIS)
        return state, trace[None], global_best

    return jax.jit(_run)


# Python int, NOT a jnp scalar: a module-level device array would
# initialize the default backend at import time, silently defeating the
# engine's later jax_platforms switch (backend="cpu")
_SENTINEL = 2 ** 31 - 1


def make_polish_runner(mesh: Mesh, cfg: ga.GAConfig):
    """Initial-population LS polish as its own dispatchable program:
    `polish(pa, key, state, n_sweeps) -> state` runs up to `n_sweeps`
    (a RUNTIME argument) convergence-bounded sweep passes on every
    island's population and re-evaluates.

    The reference LS-polishes its initial population before generation 0
    (ga.cpp:429-434) with the clock checked inside the loop
    (Solution.cpp:499); fusing that polish into one init dispatch made
    it unboundable — a 30-pass converge polish at comp scale can eat a
    whole 60 s budget in one dispatch. Chunked dispatches of a few
    passes each give the engine clock checks between chunks, and the
    runtime sweep count means one compile serves every chunk size.

    Returns `(state, stats)` where stats = stacked (penalty, hcv, scv)
    as one (3, n_islands*pop) int32 array — the engine's between-chunk
    bookkeeping (stall detection + logEntry emission) then costs ONE
    host fetch per chunk instead of three (each fetch is a multi-second
    round trip on tunneled devices; VERDICT round-3 weak #3)."""
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(),
                  ga.PopState(slots=P(AXIS), rooms=P(AXIS), penalty=P(AXIS),
                              hcv=P(AXIS), scv=P(AXIS)), P()),
        out_specs=(ga.PopState(slots=P(AXIS), rooms=P(AXIS), penalty=P(AXIS),
                               hcv=P(AXIS), scv=P(AXIS)), P(None, AXIS)),
        check_vma=False)
    def _polish(pa, key, state, n_sweeps):
        from timetabling_ga_tpu.ops.sweep import sweep_local_search
        my_key = jax.random.fold_in(key, lax.axis_index(AXIS))
        slots, rooms = sweep_local_search(
            pa, my_key, state.slots, state.rooms, n_sweeps=n_sweeps,
            swap_block=cfg.ls_swap_block, converge=True,
            block_events=cfg.ls_block_events, sideways=cfg.ls_sideways,
            hot_k=cfg.ls_hot_k, p3=cfg.p3)
        st = ga.evaluate(pa, slots, rooms)
        stats = jnp.stack([st.penalty, st.hcv, st.scv])
        return st, stats

    return jax.jit(_polish)


def make_island_runner_dynamic(mesh: Mesh, cfg: ga.GAConfig,
                               max_gens: int):
    """Like `make_island_runner(n_epochs=1)` but the generation count is
    a RUNTIME argument `n_gens <= max_gens`: `run(pa, key, state, n_gens)`.

    One compilation serves every tail size, so the engine can spend the
    last fraction of a wall-clock budget (-t, Control.cpp:62-68) on a
    right-sized dispatch instead of idling — the reference wastes nothing
    there because it checks its clock before every LS candidate
    (Solution.cpp:499); our granularity is one generation. Trace rows at
    index >= n_gens hold INT_MAX sentinels (the host slices them off).
    Migration still closes the epoch (ga.cpp:522-535 cadence).
    """
    n_islands = mesh.devices.size

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(),
                  ga.PopState(slots=P(AXIS), rooms=P(AXIS), penalty=P(AXIS),
                              hcv=P(AXIS), scv=P(AXIS)), P()),
        out_specs=(ga.PopState(slots=P(AXIS), rooms=P(AXIS),
                               penalty=P(AXIS), hcv=P(AXIS), scv=P(AXIS)),
                   P(AXIS), P()),
        check_vma=False)
    def _run(pa, key, state, n_gens):
        my_key = jax.random.fold_in(key, lax.axis_index(AXIS))
        tr0 = jnp.full((max_gens, 2), _SENTINEL, jnp.int32)

        def body(i, carry):
            st, tr = carry
            st = ga.generation(pa, jax.random.fold_in(my_key, i), st, cfg)
            tr = lax.dynamic_update_index_in_dim(
                tr, jnp.stack([st.hcv[0], st.scv[0]]), i, 0)
            return st, tr

        state, trace = lax.fori_loop(0, n_gens, body, (state, tr0))
        state = _migrate(state, n_islands)
        global_best = lax.pmin(state.penalty[0], AXIS)
        return state, trace[None, None], global_best

    return jax.jit(_run)
