"""Distributed execution: island model over a device mesh.

The reference's MPI layer (one GA island per rank, bidirectional ring
migration, ga.cpp:370-541) becomes a `jax.sharding.Mesh` axis: islands are
shards of the population tensor, migration is `lax.ppermute` over ICI, and
the global best is `lax.pmin` (replacing MPI_Allreduce MIN, ga.cpp:237).
"""

from timetabling_ga_tpu.parallel.islands import (
    make_mesh,
    init_island_population,
    make_island_runner,
)
