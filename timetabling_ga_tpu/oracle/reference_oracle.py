"""Scalar Python oracle implementing the reference's fitness semantics.

This module is deliberately slow and literal: it transcribes the *meaning*
of the reference's evaluation routines (Solution.cpp:63-170) so the batched
TPU kernels can be tested for exact integer equality against it. It is used
only by tests and never on the hot path.

The reference has no tests (SURVEY.md section 4); this oracle is the
ground-truth half of the test strategy built to replace that gap.
"""

from __future__ import annotations

import numpy as np


def oracle_hcv(problem, slots, rooms) -> int:
    """Hard-constraint violations of one solution.

    Semantics of Solution::computeHcv (Solution.cpp:141-160):
      (a) +1 for each unordered pair of events sharing (timeslot, room)
      (b) +1 for each unordered pair of correlated events sharing a timeslot
      (c) +1 for each event placed in an unsuitable room
    """
    e = problem.n_events
    hcv = 0
    for i in range(e):
        for j in range(i + 1, e):
            if slots[i] == slots[j] and rooms[i] == rooms[j]:
                hcv += 1
            if slots[i] == slots[j] and problem.conflict[i][j]:
                hcv += 1
        if not problem.possible[i][rooms[i]]:
            hcv += 1
    return hcv


def oracle_feasible(problem, slots, rooms) -> bool:
    """Solution::computeFeasibility (Solution.cpp:63-84): hcv == 0."""
    return oracle_hcv(problem, slots, rooms) == 0


def oracle_scv(problem, slots, rooms=None) -> int:
    """Soft-constraint violations of one solution.

    Semantics of Solution::computeScv (Solution.cpp:86-139):
      (a) class in the last slot of a day: +studentNumber[e] per event
      (b) per student: each class that is the >=3rd consecutive attended
          slot within one day: +1 ("more than two in a row")
      (c) per student per day with exactly one attended slot: +1

    Attendance per (student, slot) is binary: the reference breaks out of
    its event scan after the first attended event in the slot
    (Solution.cpp:105-114), so double-booked slots still count once.
    """
    spd = problem.slots_per_day
    n_slots = problem.n_days * spd
    scv = 0
    for i in range(problem.n_events):
        if slots[i] % spd == spd - 1:
            scv += int(problem.student_count[i])

    # binary attendance matrix (student, slot)
    att = np.zeros((problem.n_students, n_slots), dtype=bool)
    for e in range(problem.n_events):
        t = int(slots[e])
        for s in range(problem.n_students):
            if problem.attends[s][e]:
                att[s, t] = True

    for s in range(problem.n_students):
        consecutive = 0
        for t in range(n_slots):
            if t % spd == 0:
                consecutive = 0
            if att[s, t]:
                consecutive += 1
                if consecutive > 2:
                    scv += 1
            else:
                consecutive = 0
        for d in range(problem.n_days):
            day = att[s, d * spd:(d + 1) * spd]
            if day.sum() == 1:
                scv += 1
    return scv


def oracle_penalty(problem, slots, rooms) -> int:
    """Solution::computePenalty (Solution.cpp:162-170):
    scv if feasible else 1_000_000 + hcv."""
    h = oracle_hcv(problem, slots, rooms)
    if h == 0:
        return oracle_scv(problem, slots, rooms)
    return 1_000_000 + h


def oracle_reported_evaluation(problem, slots, rooms) -> int:
    """The *reported* evaluation used by the JSONL log for infeasible
    solutions: hcv * 1_000_000 + scv (ga.cpp:191, 218, 247). Note this
    differs from the internal penalty formula — both are kept."""
    return (oracle_hcv(problem, slots, rooms) * 1_000_000
            + oracle_scv(problem, slots, rooms))


class ParkMillerLCG:
    """Park-Miller minimal-standard LCG with Schrage's trick.

    Host-side oracle for the reference RNG (Random.cc:27-37,
    IA=16807 IM=2^31-1 IQ=127773 IR=2836). The TPU framework uses
    threefry keys (jax.random) — bit-parity with this generator under
    vmap is impossible and not a goal; this exists so golden tests can
    reproduce reference-side random choices when needed.
    """

    IA, IM, IQ, IR = 16807, 2147483647, 127773, 2836
    AM = 1.0 / 2147483647

    def __init__(self, seed: int):
        self.seed = int(seed)

    def next(self) -> float:
        k = self.seed // self.IQ
        self.seed = self.IA * (self.seed - k * self.IQ) - self.IR * k
        if self.seed < 0:
            self.seed += self.IM
        return self.AM * self.seed
