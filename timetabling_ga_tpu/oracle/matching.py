"""Exact bipartite maximum-matching oracle (host-side, scalar).

The reference's primary room-assignment path is an exact per-timeslot
maximum matching: `Solution::maxMatching` (Solution.cpp:836-849) augments
with `networkFlow`'s priority-first search (852-891) until no augmenting
path exists. The TPU kernels use fixed-shape approximations (greedy
most-constrained-first, optionally + bounded augmentation; ops/rooms.py),
so this module provides the ground truth to measure them against:
Hopcroft–Karp on (events-in-slot) x (suitable rooms).

Host/test/measurement use only — never on a production device path.
"""

from __future__ import annotations

from collections import deque
from typing import List, Sequence

import numpy as np

_INF = float("inf")


def hopcroft_karp(adj: Sequence[Sequence[int]], n_right: int) -> List[int]:
    """Maximum bipartite matching. adj[i] = right vertices of left i.

    Returns match_left: for each left vertex, its matched right vertex or
    -1. O(E * sqrt(V)); exact.
    """
    n_left = len(adj)
    match_l = [-1] * n_left
    match_r = [-1] * n_right
    dist = [0.0] * n_left

    def bfs() -> bool:
        q = deque()
        for u in range(n_left):
            if match_l[u] == -1:
                dist[u] = 0.0
                q.append(u)
            else:
                dist[u] = _INF
        found = False
        while q:
            u = q.popleft()
            for v in adj[u]:
                w = match_r[v]
                if w == -1:
                    found = True
                elif dist[w] == _INF:
                    dist[w] = dist[u] + 1
                    q.append(w)
        return found

    def dfs(u: int) -> bool:
        for v in adj[u]:
            w = match_r[v]
            if w == -1 or (dist[w] == dist[u] + 1 and dfs(w)):
                match_l[u] = v
                match_r[v] = u
                return True
        dist[u] = _INF
        return False

    while bfs():
        for u in range(n_left):
            if match_l[u] == -1:
                dfs(u)
    return match_l


def max_matching_size_per_slot(problem, slots: np.ndarray) -> np.ndarray:
    """For one solution's (E,) slot assignment: the exact maximum number
    of events that can get a distinct suitable room, per slot (T,).

    This is the quantity the reference's assignRooms achieves per slot;
    the per-slot clash+unsuitable hcv of any room assignment is bounded
    below by (#events-in-slot - max_matching)."""
    slots = np.asarray(slots)
    T = problem.n_days * problem.slots_per_day
    possible = np.asarray(problem.possible)
    out = np.zeros(T, dtype=np.int64)
    for t in range(T):
        evs = np.nonzero(slots == t)[0]
        if evs.size == 0:
            continue
        adj = [np.nonzero(possible[e])[0].tolist() for e in evs]
        match = hopcroft_karp(adj, problem.n_rooms)
        out[t] = sum(1 for m in match if m >= 0)
    return out


def room_hcv_lower_bound(problem, slots: np.ndarray) -> int:
    """Minimum possible (pair-clash + unsuitable) hcv contribution of ANY
    room assignment for the given slots: each slot's deficiency
    (#events - max matching) costs at least 1 each (an unmatched event
    either shares a room or sits in an unsuitable one)."""
    slots = np.asarray(slots)
    T = problem.n_days * problem.slots_per_day
    counts = np.bincount(slots, minlength=T)
    return int((counts - max_matching_size_per_slot(problem, slots)).sum())


def assignment_room_hcv(problem, slots: np.ndarray,
                        rooms: np.ndarray) -> int:
    """The (pair-clash + unsuitable) hcv a concrete room assignment
    incurs — the matcher-attributable part of hcv (correlation clashes
    are slot-only and match-independent)."""
    slots = np.asarray(slots)
    rooms = np.asarray(rooms)
    possible = np.asarray(problem.possible)
    T = problem.n_days * problem.slots_per_day
    R = problem.n_rooms
    occ = np.zeros((T, R), dtype=np.int64)
    np.add.at(occ, (slots, rooms), 1)
    pair = int((occ * (occ - 1) // 2).sum())
    unsuit = int((~possible[np.arange(len(slots)), rooms]).sum())
    return pair + unsuit
