from timetabling_ga_tpu.oracle.reference_oracle import (
    oracle_hcv,
    oracle_scv,
    oracle_feasible,
    oracle_penalty,
    oracle_reported_evaluation,
    ParkMillerLCG,
)
