"""`tt submit` — the stdlib solve client.

POSTs one `.tim` instance to a gateway (or directly to a replica —
same protocol), then polls the job to completion and prints the final
state as JSON on stdout:

    tt submit http://127.0.0.1:8070 comp01.tim -s 42 \
        --generations 200 --priority 5
    tt submit URL instance.tim --no-wait        just the job id
    tt submit URL instance.tim --records        include the record tail
    tt submit URL instance.tim --records-out job.jsonl
        write the job's record tail as JSONL LINES to a file — the
        same stream an unrouted solve emits, ready for `tt stats
        job.jsonl` or `tt trace --job ID job.jsonl gateway.jsonl`
        (the fleet observatory's stitched timeline) without shell
        jq surgery on the JSON view

Pure stdlib (urllib + json): it must run from any machine that can
reach the fleet, with no solver stack installed. Exit status: 0 when
the job reaches `done`, 1 for any other terminal state, 2 for
transport errors.
"""

from __future__ import annotations

import json
import sys
import time

from timetabling_ga_tpu.fleet.gateway import TERMINAL
from timetabling_ga_tpu.fleet.replicas import FleetHTTPError, http_json

_USAGE = """\
usage: python -m timetabling_ga_tpu submit URL INSTANCE.tim [flags]

submit one instance to a fleet gateway (or a single replica) and wait:
  --id <str>            job id (default: server-assigned)
  --tenant <str>        tenant tag for usage metering (tt-meter,
                        README "Usage metering"): every share of
                        fleet capacity the job consumes is attributed
                        to this tag — `tt usage URL` reports it
  --priority <int>      scheduling priority (higher first)
  -s <int>              seed
  --generations <int>   generation budget
  --deadline <float>    wall-clock deadline, seconds
  --poll <float>        poll interval, seconds (default 0.5)
  --timeout <float>     give up after this many seconds (default 3600)
  --records             print the job-tagged record tail too
  --records-out <path>  write the record tail as JSONL lines to this
                        file (tt stats / tt trace input)
  --snapshot <path>     warm-start the job from a wire snapshot JSON
                        file (serve/snapshot.py — README "Fleet
                        resume"): the job resumes at the snapshot's
                        progress instead of generation 0; the file is
                        a GET /v1/jobs/<id>?snapshot=1 view's
                        "snapshot" object, or the object itself
                        (with --edit-of it is the BASE job's snapshot
                        to transplant from instead of the gateway's
                        cached/fetched one)
  --edit-of <job id>    incremental re-solve (tt-edit, README
                        "Incremental re-solve"): submit INSTANCE.tim
                        as an EDIT of the named base job — the
                        gateway resolves the base instance and its
                        freshest snapshot, the replica diffs the two,
                        transplants the base population onto the
                        edited instance, and solves under the
                        anchored objective; the result carries
                        `edit_distance` (events moved vs the base
                        solution)
  --edit-ops <path>     JSON op list (the serve/editsolve.py grammar:
                        add_event / remove_event / set_attendance /
                        set_event_features / set_room_size /
                        set_room_features) applied to the base
                        instead of a full edited instance — INSTANCE
                        may then be '-'
  --anchor-weight <int> soft penalty per carried event placed away
                        from the base solution's slot (default 1;
                        0 = solve the plain objective, bit-identical
                        to an unanchored stream)
  --no-wait             print the job id and exit without polling
  -h, --help            show this message and exit"""


def submit_and_wait(url: str, payload: dict, poll: float = 0.5,
                    timeout: float = 3600.0, wait: bool = True):
    """POST /v1/solve then poll GET /v1/jobs/<id> until terminal.
    Returns the final job view (or the accept reply when not
    waiting). Raises FleetHTTPError/OSError on transport failure and
    TimeoutError when the budget runs out."""
    url = url.rstrip("/")
    accepted = http_json("POST", url + "/v1/solve", payload,
                         ok=(200, 202))
    if not wait:
        return accepted
    job_id = accepted["id"]
    deadline = time.monotonic() + timeout
    from urllib.parse import quote
    while True:
        # steady-state polls are STATE-ONLY (the record tail is the
        # expensive part of the view — same discipline as the
        # gateway's dispatcher); the full view is fetched once, at
        # terminal
        view = http_json(
            "GET", f"{url}/v1/jobs/{quote(job_id)}?records=0",
            ok=(200,))
        if view.get("state") in TERMINAL:
            return http_json(
                "GET", f"{url}/v1/jobs/{quote(job_id)}", ok=(200,))
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"job {job_id} still {view.get('state')!r} after "
                f"{timeout:.0f}s")
        time.sleep(poll)


def main_submit(argv) -> int:
    """`tt submit` entry point (cli.py dispatches here)."""
    args = list(argv)
    if not args or args[0] in ("-h", "--help"):
        print(_USAGE)
        return 0
    if len(args) < 2:
        print(_USAGE, file=sys.stderr)
        return 2
    url, instance = args[0], args[1]
    rest = args[2:]
    payload: dict = {}
    poll, timeout = 0.5, 3600.0
    wait = True
    records = False
    records_out = None
    i = 0
    flag_types = {"--id": ("id", str), "--priority": ("priority", int),
                  "--tenant": ("tenant", str),
                  "-s": ("seed", int),
                  "--generations": ("generations", int),
                  "--deadline": ("deadline", float)}
    edit_of = None
    edit_ops = None
    anchor_w = None
    while i < len(rest):
        a = rest[i]
        if a in ("-h", "--help"):
            print(_USAGE)
            return 0
        if a == "--records":
            records = True
            i += 1
            continue
        if a == "--records-out":
            if i + 1 >= len(rest):
                print("flag --records-out needs a value",
                      file=sys.stderr)
                return 2
            records_out = rest[i + 1]
            i += 2
            continue
        if a == "--snapshot":
            if i + 1 >= len(rest):
                print("flag --snapshot needs a value",
                      file=sys.stderr)
                return 2
            try:
                with open(rest[i + 1], "r", encoding="utf-8") as fh:
                    snap = json.load(fh)
            except (OSError, ValueError) as e:
                print(f"tt submit: bad snapshot file: {e}",
                      file=sys.stderr)
                return 2
            # accept either the bare wire object or a saved
            # ?snapshot=1 job view wrapping one
            if isinstance(snap, dict) and "snapshot" in snap \
                    and "npz" not in snap:
                snap = snap["snapshot"]
            payload["snapshot"] = snap
            i += 2
            continue
        if a == "--edit-of":
            if i + 1 >= len(rest):
                print("flag --edit-of needs a value", file=sys.stderr)
                return 2
            edit_of = rest[i + 1]
            i += 2
            continue
        if a == "--edit-ops":
            if i + 1 >= len(rest):
                print("flag --edit-ops needs a value",
                      file=sys.stderr)
                return 2
            try:
                with open(rest[i + 1], "r", encoding="utf-8") as fh:
                    ops = json.load(fh)
            except (OSError, ValueError) as e:
                print(f"tt submit: bad edit-ops file: {e}",
                      file=sys.stderr)
                return 2
            # accept the bare op list or an {"ops": [...]} wrapper
            if isinstance(ops, dict) and "ops" in ops:
                ops = ops["ops"]
            edit_ops = ops
            i += 2
            continue
        if a == "--anchor-weight":
            if i + 1 >= len(rest):
                print("flag --anchor-weight needs a value",
                      file=sys.stderr)
                return 2
            try:
                anchor_w = int(rest[i + 1])
            except ValueError:
                print(f"flag --anchor-weight wants int, got "
                      f"{rest[i + 1]!r}", file=sys.stderr)
                return 2
            i += 2
            continue
        if a == "--no-wait":
            wait = False
            i += 1
            continue
        if a in ("--poll", "--timeout"):
            if i + 1 >= len(rest):
                print(f"flag {a} needs a value", file=sys.stderr)
                return 2
            try:
                if a == "--poll":
                    poll = float(rest[i + 1])
                else:
                    timeout = float(rest[i + 1])
            except ValueError:
                print(f"flag {a} wants a number, got "
                      f"{rest[i + 1]!r}", file=sys.stderr)
                return 2
            i += 2
            continue
        if a not in flag_types:
            print(f"unknown flag: {a}", file=sys.stderr)
            return 2
        if i + 1 >= len(rest):
            print(f"flag {a} needs a value", file=sys.stderr)
            return 2
        key, typ = flag_types[a]
        try:
            payload[key] = typ(rest[i + 1])
        except ValueError:
            # usage errors share the transport-error contract: one
            # line on stderr, status 2, never a traceback
            print(f"flag {a} wants {typ.__name__}, got "
                  f"{rest[i + 1]!r}", file=sys.stderr)
            return 2
        i += 2
    if edit_ops is not None and edit_of is None:
        print("--edit-ops needs --edit-of", file=sys.stderr)
        return 2
    try:
        tim_text = None
        if instance != "-":
            with open(instance, "r") as fh:
                tim_text = fh.read()
        if edit_of is not None:
            edit: dict = {"base": edit_of}
            if edit_ops is not None:
                edit["ops"] = edit_ops
            elif tim_text is not None:
                edit["edited"] = {"tim": tim_text}
            else:
                print("tt submit: --edit-of needs an edited "
                      "INSTANCE.tim or --edit-ops", file=sys.stderr)
                return 2
            if anchor_w is not None:
                edit["w_anchor"] = anchor_w
            if "snapshot" in payload:
                # with --edit-of the snapshot file is the BASE job's
                # wire to transplant from, not this job's own resume
                edit["snapshot"] = payload.pop("snapshot")
            payload["edit"] = edit
        elif tim_text is not None:
            payload["tim"] = tim_text
        else:
            print("tt submit: INSTANCE '-' needs --edit-of with "
                  "--edit-ops", file=sys.stderr)
            return 2
        view = submit_and_wait(url, payload, poll=poll,
                               timeout=timeout, wait=wait)
    except (FleetHTTPError, OSError, TimeoutError) as e:
        # a missing instance file and a dead gateway exit the same
        # way: status 2 with one line, never a traceback
        print(f"tt submit: {e}", file=sys.stderr)
        return 2
    if not wait:
        print(json.dumps(view))
        return 0
    if records_out is not None:
        # the record tail AS A JSONL STREAM — byte-layout compatible
        # with an unrouted solve's -o file, so tt stats / tt trace
        # (incl. the stitched fleet timeline) read it directly
        try:
            with open(records_out, "w", encoding="utf-8") as fh:
                for rec in view.get("records") or []:
                    fh.write(json.dumps(rec, separators=(",", ":"))
                             + "\n")
        except OSError as e:
            print(f"tt submit: {e}", file=sys.stderr)
            return 2
    if not records:
        view = {k: v for k, v in view.items() if k != "records"}
    print(json.dumps(view))
    return 0 if view.get("state") == "done" else 1
