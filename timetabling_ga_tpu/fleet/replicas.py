"""Replica-set management: drive loops, probes, restart, drain.

Three replica shapes, one gateway-side view:

  Replica       turns a SolveService into an HTTP replica: a DRIVE
                LOOP thread owns every device call (admission prepare,
                scheduler steps, cancellation fences) and consumes a
                command inbox the HTTP handlers feed — the handler
                threads themselves only enqueue and read (TT605).
                Used in-process (tests, bench, programmatic fleets)
                via `.start()`, or as the `tt serve --http` foreground
                process via `.run()`.
  spawn_local   `tt fleet --spawn N`: one `tt serve --http` worker
                process per replica on a local port, with a respawn
                closure the prober uses for restart-on-death.
  ReplicaHandle the gateway's client-side view of ANY replica (remote
                URL, spawned process, or in-process): submit / poll /
                cancel / drain calls plus the probe state the router
                reads (readiness reasons, backlog gauge, compile-hit
                counters).

ReplicaSet owns the probe thread: every `probe_every` seconds it
refreshes each handle's `/readyz` JSON and `/metrics` families, and
after `dead_after` consecutive failed probes (or a reaped worker
process) either respawns the worker (restart-on-death, bounded by
`max_restarts`) or declares the replica dead — both reported through
`on_death`, which the gateway turns into failover.

Drain order matters: a draining replica finishes its PARKED jobs
first (the drive loop keeps stepping until the queue has no active
job), then closes its service — so the writer drains, the record
stream completes, and only then does the process exit. `/readyz`
reports `draining` the whole time so routers stop sending work
(obs/http.py readiness).
"""

from __future__ import annotations

import collections
import io
import itertools
import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

from timetabling_ga_tpu.fleet.gateway import TERMINAL, ApiHandler
from timetabling_ga_tpu.obs import http as obs_http
from timetabling_ga_tpu.obs import scrape as obs_scrape
from timetabling_ga_tpu.runtime import faults, jsonl
from timetabling_ga_tpu.runtime.config import FleetConfig, ServeConfig

# per-job record-tail bound on a replica: GET /v1/jobs/<id> serves at
# most this many records (a fleet job's stream is a handful of
# logEntries + lifecycle records; 4096 only guards a pathological
# tenant from holding the replica's memory)
TAIL_CAP = int(os.environ.get("TT_FLEET_TAIL_CAP", "4096"))
# how many JOBS keep a tail (and how many rejected-submission entries
# the front index keeps): beyond this the oldest are evicted — a
# long-running replica must not hold every record tail it ever served
# (the gateway has the same policy as --retain-terminal)
TAIL_JOBS = int(os.environ.get("TT_FLEET_TAIL_JOBS", "4096"))


# ------------------------------------------------------------- HTTP client


class FleetHTTPError(RuntimeError):
    """Non-OK HTTP status from a replica/gateway."""

    def __init__(self, status: int, url: str, detail):
        self.status = status
        self.detail = detail
        super().__init__(f"HTTP {status} from {url}: "
                         f"{str(detail)[:200]}")


def http_json(method: str, url: str, obj=None, timeout: float = 5.0,
              ok: tuple = (200, 202), headers=None):
    """One JSON-in/JSON-out HTTP call (stdlib urllib). 4xx/5xx bodies
    are parsed too; statuses outside `ok` raise FleetHTTPError with
    the parsed detail attached. `headers` adds request headers (the
    gateway ships a job's cross-process flow id as `X-TT-Flow`)."""
    data = None
    hdrs = dict(headers or {})
    if obj is not None:
        data = json.dumps(obj).encode()
        hdrs["Content-Type"] = "application/json"
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            status = resp.status
            body = resp.read()
    except urllib.error.HTTPError as e:
        status = e.code
        body = e.read()
    try:
        parsed = json.loads(body) if body else {}
    except ValueError:
        parsed = {"raw": body.decode("utf-8", "replace")[:200]}
    if status not in ok:
        raise FleetHTTPError(status, url, parsed)
    return parsed


def http_text(url: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8", "replace")


# ------------------------------------------------------------ record tail


class JobTail:
    """Out-stream tee keeping a per-job tail of job-tagged records.

    Sits between the service's AsyncWriter and the real output stream:
    every line still reaches the stream byte-identically (the tee adds
    no records and reorders nothing), and each parsed record carrying
    a `job` tag lands in that job's tail, which GET /v1/jobs/<id>
    serves. Runs on the WRITER thread (the parse cost rides the
    off-dispatch-path worker, like every other serialization cost)."""

    def __init__(self, stream, cap: int = TAIL_CAP,
                 max_jobs: int = TAIL_JOBS):
        self._stream = stream
        self._cap = cap
        self._max_jobs = max_jobs
        self._buf = ""
        self._tails: dict = {}       # insertion-ordered: FIFO evict
        self._counts: dict = {}      # records INGESTED per job — a
        #                              ring holding exactly cap
        #                              records is only truncated if
        #                              MORE than cap ever arrived
        self._lock = threading.Lock()

    # -- stream protocol (AsyncWriter's view) ---------------------------

    def write(self, s: str) -> None:
        self._stream.write(s)
        self._buf += s
        while "\n" in self._buf:
            line, self._buf = self._buf.split("\n", 1)
            self._ingest(line)

    def flush(self) -> None:
        self._stream.flush()

    # -- tail store -----------------------------------------------------

    def _ingest(self, line: str) -> None:
        try:
            rec = json.loads(line)
        except ValueError:
            return
        if not isinstance(rec, dict) or not rec:
            return
        kind = next(iter(rec))
        body = rec.get(kind)
        job = body.get("job") if isinstance(body, dict) else None
        if job is None:
            return
        with self._lock:
            tail = self._tails.get(str(job))
            if tail is None:
                # a bounded RING per job — an over-cap stream keeps
                # its LAST records, so the terminal jobEntry (what
                # settle logic and clients need most) always survives
                # truncation; only the prefix is lost
                tail = collections.deque(maxlen=self._cap)
                self._tails[str(job)] = tail
            tail.append(rec)
            self._counts[str(job)] = self._counts.get(str(job), 0) + 1
            while len(self._tails) > self._max_jobs:
                # oldest job's tail goes (dict insertion order): the
                # stream itself is the durable copy; the tail only
                # feeds GET /v1/jobs/<id>
                evicted = next(iter(self._tails))
                self._tails.pop(evicted)
                self._counts.pop(evicted, None)

    def tail(self, job_id: str) -> list:
        with self._lock:
            return list(self._tails.get(str(job_id), ()))

    def truncated(self, job_id: str) -> bool:
        """True when the ring actually DROPPED records (more arrived
        than it holds) — a records-identity comparison cannot hold.
        A stream of exactly cap records is complete, not truncated."""
        with self._lock:
            t = self._tails.get(str(job_id))
            return (t is not None
                    and self._counts.get(str(job_id), 0) > len(t))


# ----------------------------------------------------------- the replica


def payload_problem(payload: dict):
    """Parse a submit payload into a Problem — the FULL parse, on the
    replica that solves it (the gateway only ever reads the header)."""
    from timetabling_ga_tpu.problem import load_tim
    kw = {}
    if "n_days" in payload:
        kw["n_days"] = int(payload["n_days"])
    if "slots_per_day" in payload:
        kw["slots_per_day"] = int(payload["slots_per_day"])
    if "problem" in payload:
        return problem_from_json(payload["problem"])
    return load_tim(str(payload["tim"]), **kw)


def problem_from_json(obj: dict):
    """Pre-parsed problem JSON -> Problem (the POST /v1/solve
    `{"problem": {...}}` form): raw counts + the four reference
    arrays; derived matrices are recomputed here, never trusted from
    the wire."""
    import numpy as np

    from timetabling_ga_tpu.problem import (
        DAYS_DEFAULT, SLOTS_PER_DAY_DEFAULT, derive)
    try:
        E, R, F, S = (int(obj[k]) for k in (
            "n_events", "n_rooms", "n_features", "n_students"))
        return derive(
            E, R, F, S,
            np.asarray(obj["room_size"], np.int32),
            np.asarray(obj["attends"], np.int8),
            np.asarray(obj["room_features"], np.int8),
            np.asarray(obj["event_features"], np.int8),
            n_days=int(obj.get("n_days", DAYS_DEFAULT)),
            slots_per_day=int(obj.get("slots_per_day",
                                      SLOTS_PER_DAY_DEFAULT)))
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"bad problem JSON: {e}") from None


def problem_to_json(problem) -> dict:
    """Problem -> the wire form problem_from_json accepts."""
    import numpy as np
    return {"n_events": problem.n_events, "n_rooms": problem.n_rooms,
            "n_features": problem.n_features,
            "n_students": problem.n_students,
            "n_days": problem.n_days,
            "slots_per_day": problem.slots_per_day,
            "room_size": np.asarray(problem.room_size).tolist(),
            "attends": np.asarray(problem.attends).tolist(),
            "room_features":
                np.asarray(problem.room_features).tolist(),
            "event_features":
                np.asarray(problem.event_features).tolist()}


class ReplicaApi:
    """The replica front's handler surface: enqueue-or-read-only
    (TT605). Submissions and cancellations become inbox commands the
    drive loop executes at its next control fence; job views read the
    queue's job table and the record tail directly."""

    def __init__(self, replica: "Replica"):
        self._r = replica

    def accept_solve(self, payload: dict, flow: int = 0,
                     resubmit: bool = False):
        r = self._r
        if r.draining:
            return 503, {"error": "draining", "reasons": ["draining"]}
        if not r.driving():
            return 503, {"error": "drive loop down"}
        with r.index_lock:
            job_id = str(payload.get("id")
                         or f"{r.name}-{next(r.auto_id)}")
            if job_id in r.index or job_id in r.svc.queue:
                return 409, {"error": "duplicate job id", "id": job_id}
            r.index[job_id] = {"state": "accepted"}
        # `flow` is the gateway's X-TT-Flow header (0 = none): the
        # drive loop threads it into Job.flow so every replica-side
        # span of this job CONTINUES the gateway's causal chain.
        # `resubmit` is its X-TT-Resubmit: a gateway RESEND skips the
        # tenant `jobs` count — the first admission already billed it
        r.inbox.put(("submit", job_id, dict(payload, id=job_id), flow,
                     resubmit))
        return 202, {"id": job_id, "state": "accepted"}

    def job_view(self, job_id: str, with_records: bool = True,
                 with_snapshot: bool = False):
        r = self._r
        try:
            job = r.svc.queue.get(job_id)
        except KeyError:
            job = None
        if job is None:
            with r.index_lock:
                info = r.index.get(job_id)
            if info is None:
                return 404, {"error": f"unknown job {job_id!r}"}
            view = {"id": job_id, "state": info["state"],
                    "error": info.get("error"), "result": None}
        else:
            view = {"id": job_id, "state": job.state,
                    "gens": job.gens_done, "error": job.error,
                    "result": job.result}
        if with_records:
            # serializing a long tail is the expensive part of this
            # view — ?records=0 (the gateway's steady-state poll)
            # skips it and fetches the tail once, at terminal
            view["records"] = r.tail.tail(job_id)
            view["records_truncated"] = r.tail.truncated(job_id)
        if with_snapshot and job is not None:
            # a device-RESIDENT job's ship unit is its last HOST
            # fence's (older but consistent — serve/scheduler.py
            # RESIDENCY); ask the drive loop to park every resident
            # group at its next control fence so the next refresh
            # ships current progress, and mark THIS job ship_hot so
            # a polling gateway's resume cache stays within one
            # quantum of the live cursor (its group keeps parking
            # instead of re-entering residency between polls).
            # Flag-only: this handler thread must never touch the
            # device (TT605)
            job.ship_hot = True
            r.svc.scheduler.request_flush()
            # `?snapshot=1`: publish the job's latest park-fence ship
            # unit (serve/snapshot.py ShipUnit — one consistent
            # state+record-prefix pair the drive loop replaced
            # wholesale). The expensive npz pack runs HERE, on this
            # handler thread (memoized per fence): fault site
            # `snapshot_ship` — a hang parks this one handler, the
            # drive loop and writer never wait; a die is absorbed as a
            # dropped connection, exactly like the `scrape` site
            ship = job.ship
            if ship is not None:
                try:
                    faults.maybe_fail("snapshot_ship")
                    view["snapshot"] = ship.pack()
                except SystemExit:
                    return None, None        # drop the connection
                view["snapshot_records"] = list(ship.records)
                if ship.records_bytes is None:
                    # measured once per fence (memoized on the unit,
                    # handler thread): the gateway budgets its cache
                    # on this number instead of re-serializing the
                    # prefix on its dispatcher at every refresh
                    ship.records_bytes = sum(
                        len(json.dumps(r)) for r in ship.records)
                view["snapshot_records_bytes"] = ship.records_bytes
                view["snapshot_truncated"] = bool(ship.truncated)
                ship.served = True           # preempt drain's signal
        return 200, view

    def jobs_view(self):
        """Bulk STATE-ONLY view of every job this replica knows — one
        response serves the gateway's whole steady-state poll tick
        for this replica (no record tails, no results: those are
        fetched per job, once, at terminal). Read order matters: the
        INDEX first, then the queue (which overrides) — a submission
        is in the index until AFTER it enters the queue, so it can
        never be absent from both; the other order has a window the
        gateway would misread as 'replica lost the job' and fail
        over, double-solving it."""
        r = self._r
        out = {}
        with r.index_lock:
            for job_id, info in r.index.items():
                out[job_id] = {"state": info["state"]}
        for job in list(r.svc.queue._jobs.values()):
            out[job.id] = {"state": job.state, "gens": job.gens_done}
        return 200, {"jobs": out}

    def accept_cancel(self, job_id: str):
        r = self._r
        known = job_id in r.svc.queue
        if not known:
            with r.index_lock:
                known = job_id in r.index
        if not known:
            return 404, {"error": f"unknown job {job_id!r}"}
        r.inbox.put(("cancel", job_id))
        return 202, {"id": job_id, "cancelling": True}

    def accept_drain(self, mode: str = "graceful", replica=None):
        del replica                     # gateway-only selector
        if mode not in ("graceful", "preempt"):
            return 400, {"error": f"unknown drain mode {mode!r} "
                                  f"(graceful | preempt)"}
        r = self._r
        r.inbox.put(("drain", mode))
        return 200, {"draining": True, "mode": mode,
                     "active": len(r.svc.queue.active())}

    def fleet_view(self):
        return 404, {"error": "not a gateway (single replica)"}

    def incident_view(self):
        """GET /v1/incident: the replica's newest flight-recorder
        bundle, served FROM MEMORY (obs/flight.incident_response —
        the shared wire shape; no file I/O on this handler thread).
        404 without a recorder or before the first dump."""
        from timetabling_ga_tpu.obs.flight import incident_response
        return incident_response(self._r.svc.flight)

    def usage_view(self):
        """GET /v1/usage: this replica's tt-meter view (README "Usage
        metering") — the ledger's per-tenant totals (ITS OWN metered
        contribution: the gateway sums these fleet-wide) plus each
        known job's cumulative meter (`Job.usage`, replaced wholesale
        at park fences, so this read is torn-free). Read-only on this
        handler thread (TT607); 404 when metering is off
        (--no-usage)."""
        ledger = self._r.svc.usage
        if ledger is None:
            return 404, {"error": "usage metering off (--no-usage)"}
        from timetabling_ga_tpu.obs import usage as obs_usage
        jobs = {}
        for job in list(self._r.svc.queue._jobs.values()):
            if job.usage:
                jobs[job.id] = {"tenant": job.tenant,
                                "state": job.state,
                                "gens": job.gens_done,
                                "usage": obs_usage.rounded(job.usage)}
        return 200, {"tenants": ledger.totals(), "jobs": jobs}


class Replica:
    """One HTTP replica: SolveService + drive loop + `/v1` front.

    The drive loop is the ONLY thread that touches the device: it
    admits parsed submissions (pad + place), steps the scheduler one
    dispatch at a time, honors cancellations at control fences, and —
    once draining — runs the queue dry before closing the service
    (parked jobs finish; the writer drains; the record stream
    completes). `kill()` is the test double for a crashed replica:
    the loop stops dead, nothing finalizes, the front goes silent."""

    def __init__(self, cfg: ServeConfig, name: str = "replica",
                 out=None, registry=None, now=None):
        import dataclasses

        # deferred: this is the one fleet entry point that pulls in
        # the solver stack (jax) — gateways and clients never do
        from timetabling_ga_tpu.runtime import dispatch_core
        from timetabling_ga_tpu.serve.service import SolveService
        self.name = name
        self.cfg = cfg
        base = out
        self._close_base = False
        if base is None:
            if cfg.output:
                # APPEND: restart-on-death respawns a worker with the
                # same -o path — truncating would wipe the dead
                # incarnation's completed jobs from the only durable
                # record log
                base = open(cfg.output, "a")
                self._close_base = True
            else:
                # stdout, like line-JSON `tt serve`: a long-lived
                # replica must stream its records somewhere durable,
                # never accumulate them in memory (in-process test
                # replicas pass an explicit buffer instead)
                base = sys.stdout
        self.tail = JobTail(base)
        self.svc = SolveService(
            dataclasses.replace(cfg, output=None), out=self.tail,
            now=now, registry=registry)
        self.inbox = dispatch_core.CommandFence()
        self.index: dict = {}        # pre-admission / rejected states
        self.index_lock = threading.Lock()
        self.auto_id = itertools.count(1)
        self.draining = False
        self._preempting = False     # preempt drain: park + ship, do
        #                              NOT run the queue dry
        self._preempt_deadline = None
        self._reaped: list = []      # terminal ids, oldest first —
        #                              heavy refs released, then
        #                              forgotten beyond TAIL_JOBS
        self._signal_drain = False   # set by signal handlers (a bare
        #                              store: handlers run on the main
        #                              thread mid-bytecode and must
        #                              take NO locks — inbox.put could
        #                              deadlock against the drive
        #                              loop's own inbox.get)
        self.drained = threading.Event()
        self._killed = False
        self._thread = None
        self.front = None
        if cfg.http:
            self.front = obs_http.ObsServer(
                cfg.http, registry=self.svc.registry,
                probes={"process": lambda: True,
                        "writer": self.svc.writer.alive,
                        "drive": self.driving},
                profile=self.svc.profile_capture,
                history=self.svc.history,
                handler=ApiHandler, api=ReplicaApi(self)).start()

    @property
    def url(self) -> str:
        return self.front.url

    def driving(self) -> bool:
        """True while the drive loop can still make progress: before
        start() (foreground run() pending) or while the thread/loop
        lives."""
        if self._killed or self.drained.is_set():
            return False
        return self._thread is None or self._thread.is_alive()

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "Replica":
        """In-process mode: drive loop on a daemon thread."""
        self._thread = threading.Thread(
            target=self.run, name=f"tt-replica-{self.name}",
            daemon=True)
        self._thread.start()
        return self

    def drain(self) -> None:
        self.inbox.put(("drain",))

    def stop(self, timeout: float = 120.0) -> None:
        """Graceful stop: drain, wait for the loop to finish, close
        the front."""
        self.drain()
        self.drained.wait(timeout)
        if self.front is not None:
            self.front.close()

    def kill(self) -> None:
        """Simulate replica death (tests/bench): the drive loop exits
        WITHOUT finalizing or closing the service — running jobs
        freeze mid-flight, exactly like a crashed process — and the
        front stops answering, so the gateway's prober declares the
        replica dead and fails its jobs over."""
        self._killed = True
        if self.front is not None:
            self.front.close()
        self.inbox.put(("wake",))

    # -- the drive loop -------------------------------------------------

    def run(self) -> None:
        """Drive until drained (or killed). Foreground entry point for
        `tt serve --http`; start() wraps it in a thread."""
        try:
            while not self._killed:
                try:
                    if self._signal_drain and not self.draining:
                        # "preempt" = spot worker SIGTERM under
                        # --preempt-on-term: park + ship, don't run
                        # the queue dry
                        if self._signal_drain == "preempt":
                            self._preempt()
                        else:
                            self._set_draining()
                    cmd = self.inbox.poll()
                    if cmd is not None:
                        self._handle(cmd)
                        continue
                    if self.draining and not self.svc.queue.active():
                        if not self._preempting or self._shipped():
                            break
                    busy = False
                    if self.svc.queue.ready():
                        busy = bool(self.svc.step())
                    self._reap_terminal()
                    if not busy:
                        cmd = self.inbox.wait(timeout=0.05)
                        if cmd is not None:
                            self._handle(cmd)
                except KeyboardInterrupt:
                    # foreground mode: ^C = drain request, not a crash
                    self._set_draining()
        finally:
            if not self._killed:
                try:
                    self.svc.close()
                except Exception:
                    pass
                if self._close_base:
                    try:
                        self.tail._stream.close()
                    except Exception:
                        pass
            self.drained.set()

    def _handle(self, cmd: tuple) -> None:
        kind = cmd[0]
        if kind == "submit":
            job_id, payload = cmd[1], cmd[2]
            flow = cmd[3] if len(cmd) > 3 else 0
            resubmit = bool(cmd[4]) if len(cmd) > 4 else False
            try:
                # tt-edit: an edit payload carries no instance of its
                # own — the service derives the edited problem from
                # the spec (serve/editsolve.py applies ops / diffs,
                # attaches the anchor, transplants the population)
                problem = (None if "edit" in payload
                           else payload_problem(payload))
                self.svc.submit(
                    problem, job_id=job_id,
                    priority=int(payload.get("priority", 0)),
                    seed=payload.get("seed"),
                    generations=payload.get("generations"),
                    deadline_s=payload.get("deadline"),
                    flow=flow,
                    snapshot=payload.get("snapshot"),
                    tenant=payload.get("tenant"),
                    count_job=not resubmit,
                    edit=payload.get("edit"))
                with self.index_lock:
                    self.index.pop(job_id, None)
            except Exception as e:
                # mirror the line-JSON protocol: any submit-side
                # failure is a rejection record and the replica
                # continues — one bad tenant never takes it down
                jsonl.job_entry(self.svc.writer, job_id, "rejected",
                                reason=str(e)[:200])
                with self.index_lock:
                    self.index[job_id] = {"state": "rejected",
                                          "error": str(e)[:200]}
                    while len(self.index) > TAIL_JOBS:
                        # bounded like the tails: rejected entries of
                        # a long-running replica must not accumulate
                        self.index.pop(next(iter(self.index)))
        elif kind == "cancel":
            self.svc.cancel(cmd[1])
        elif kind == "drain":
            mode = cmd[1] if len(cmd) > 1 else "graceful"
            if mode == "preempt":
                self._preempt()
            else:
                self._set_draining()
        # "wake": loop tick only

    # -- preempt drain (README "Fleet resume") --------------------------

    def _preempt(self) -> None:
        """Cooperative preemption (POST /v1/drain?mode=preempt, or
        SIGTERM under --preempt-on-term): every active job is PARKED
        where it stands and marked `preempted` — a state the gateway
        reads as "resume me elsewhere" — and the front stays up
        serving `?snapshot=1` until every preempted job's ship unit
        has been fetched or `--preempt-grace` expires; then the loop
        exits and the service closes (the writer drains, so the
        `preempted` jobEntries and everything before them reach the
        durable log). Scale-down and spot preemption thereby lose at
        most the in-flight quantum — usually nothing, since _handle
        runs between quanta, when every job is at a park fence."""
        self._set_draining()
        if self._preempting:
            return
        self._preempting = True
        self._preempt_deadline = (time.monotonic()
                                  + self.cfg.preempt_grace)
        # park every device-resident group FIRST (_handle runs on the
        # drive loop, between quanta — a legal device fence): the ship
        # units published below then reflect real progress, not the
        # group's last pre-residency host fence
        self.svc.scheduler.flush_resident("preempt")
        from timetabling_ga_tpu.serve.queue import JobState
        for job in list(self.svc.queue.active()):
            job.state = JobState.PREEMPTED
            jsonl.job_entry(self.svc.writer, job.id, "preempted",
                            gens=job.gens_done,
                            shipped=job.ship is not None)
            self.svc.registry.counter("serve.jobs_preempted").inc()

    def _shipped(self) -> bool:
        """True when the preempt drain may exit: every preempted job's
        ship unit was fetched at least once, or the grace deadline
        passed (a spot preemption waits for nobody)."""
        if (self._preempt_deadline is not None
                and time.monotonic() >= self._preempt_deadline):
            return True
        from timetabling_ga_tpu.serve.queue import JobState
        return all(job.ship is None or job.ship.served
                   for job in self.svc.queue._jobs.values()
                   if job.state == JobState.PREEMPTED)

    def _reap_terminal(self) -> None:
        """Release terminal jobs' heavy references — the padded
        device arrays, derived problem matrices, and any lingering
        host snapshot — the moment they settle (the result dict and
        record tail keep serving GET /v1/jobs), then FORGET the
        oldest settled jobs beyond TAIL_JOBS. Without this a
        long-running replica pins every job it ever solved in HBM —
        the exact unbounded retention the gateway's
        --retain-terminal exists to prevent. The final park-fence
        SHIP UNIT is the one reference that stays: it is host bytes
        (npz b64 + a capped record prefix, no device arrays), and a
        settled job may still become an edit BASE (tt-edit) — the
        gateway's `?snapshot=1` grab of the final wire is what turns
        an edit of a finished job into a warm transplant instead of
        a cold demote. It leaves with the job at the TAIL_JOBS
        forget, the same bound the record tails live under."""
        for job in list(self.svc.queue._jobs.values()):
            if job.state in TERMINAL and job.pa_dev is not None:
                job.pa_dev = None
                job.padded = None
                job.problem = None
                job.snapshot = None
                job.ship_records = []
                self._reaped.append(job.id)
        while len(self._reaped) > TAIL_JOBS:
            self.svc.queue.forget(self._reaped.pop(0))

    def _set_draining(self) -> None:
        if not self.draining:
            self.draining = True
            # drive-loop-side registry write (handlers may not):
            # /readyz now reports `draining` until the process exits
            self.svc.registry.gauge("serve.draining").set(1.0)


def serve_http(cfg: ServeConfig) -> int:
    """`tt serve --http HOST:PORT` foreground mode (service.main_serve
    dispatches here): one replica, drive loop on the main thread,
    SIGTERM/SIGINT mapped to graceful drain."""
    import signal

    replica = Replica(cfg)
    print(f"# tt serve --http: replica on {replica.url}",
          file=sys.stderr, flush=True)

    def _drain(signum, frame):
        # lock-free by design: the handler interrupts the drive loop's
        # own thread, so queue/registry locks here could self-deadlock;
        # the loop reads the flag at its next iteration. SIGTERM on a
        # spot worker launched with --preempt-on-term maps to the
        # PREEMPT drain: park + ship every job within --preempt-grace
        # instead of running the queue dry the preemption won't wait
        # for (README "Fleet resume")
        if signum == signal.SIGTERM and cfg.preempt_on_term:
            replica._signal_drain = "preempt"
        else:
            replica._signal_drain = True

    signal.signal(signal.SIGTERM, _drain)
    signal.signal(signal.SIGINT, _drain)
    replica.run()
    if replica.front is not None:
        replica.front.close()
    return 0


# ----------------------------------------------------- gateway-side view


class ReplicaHandle:
    """The gateway's client-side view of one replica: HTTP verbs plus
    the probe state the router scores on. Probe fields are written by
    the ReplicaSet's prober thread and read by the dispatcher — plain
    attribute stores, coherent enough for routing (a stale gauge
    costs a suboptimal placement, never a wrong result)."""

    def __init__(self, name: str, url: str, proc=None, respawn=None):
        self.name = name
        self.url = url.rstrip("/")
        self.proc = proc             # subprocess.Popen for spawned
        self.respawn = respawn       # zero-arg -> fresh Popen
        self.restarts = 0
        self.fails = 0               # consecutive failed probes
        self.dead = False
        self.retired = False         # tt-scale scale-down: this
        #                              replica was DELIBERATELY
        #                              preempt-drained (fleet/
        #                              autoscaler.py) — its exit is
        #                              expected, so the prober must
        #                              not respawn it (and the scaler
        #                              stops counting it toward the
        #                              live target the moment the
        #                              retire decision lands)
        self.ok_once = False         # ever answered a probe
        self.born = time.monotonic()  # (re)spawn time: boot grace
        # -- router inputs (refreshed by probe()) -----------------------
        self.ready = False
        self.reasons: list = ["unprobed"]
        self.queue_depth = None
        self.backlog = None
        self.compile_count = 0.0
        self.compile_cache_hits = 0.0
        # device residency (serve.resident_* gauges): the autoscaler's
        # residency-aware victim choice reads these off the same
        # scrape — None until first scraped (treated as warm-unknown,
        # never preferred over a known-cold replica)
        self.resident_groups = None
        self.resident_bytes = None
        self.probe_seconds = None    # last successful probe's round
        #                              trip (/readyz + /metrics) — the
        #                              gateway's fleet.replica.* probe
        #                              latency gauge
        # -- tt-flight incident correlation (refreshed by probe()) ------
        self.flight_dumps = 0.0      # the replica's incident-dump
        #                              counter, off the SAME scrape the
        #                              router inputs ride
        self.last_incident = None    # newest bundle fetched when that
        #                              counter advanced: the gateway's
        #                              stitched bundle falls back to
        #                              this copy when the replica is
        #                              already dead at failover time
        # -- tt-meter ledger cache (refreshed by probe()) ----------------
        self.last_usage = None       # the replica's newest /v1/usage
        #                              payload: a DEAD replica's last-
        #                              scraped ledger keeps feeding the
        #                              gateway's fleet-wide /v1/usage
        #                              aggregation (obs/usage.aggregate
        #                              — metered work never vanishes
        #                              from the bill with its replica)
        self.usage_base = None       # RETIRED incarnations' combined
        #                              ledger: a respawned worker's
        #                              fresh (near-empty) payload must
        #                              ADD to the dead incarnation's,
        #                              never replace it — _declare_dead
        #                              folds last_usage in here before
        #                              the respawn, and usage_payload()
        #                              serves the sum. A STATIC
        #                              replica restarted behind our
        #                              back has no respawn event to
        #                              fold on — the prober instead
        #                              detects the restart by its
        #                              BACKWARD-moving usage counters
        #                              on the next scrape
        #                              (obs/usage.progress, the
        #                              flight-dump counter
        #                              discipline) and folds the
        #                              cached payload then.
        #                              The (base, last) PAIR is read
        #                              and written under _usage_lock:
        #                              unlike the single-attribute
        #                              probe gauges, retiring is a
        #                              two-field move, and a gateway
        #                              /v1/usage racing it would
        #                              double-count (or drop) a whole
        #                              incarnation's bill
        self._usage_lock = threading.Lock()

    # -- probe ----------------------------------------------------------

    def probe(self, timeout: float) -> bool:
        """One readiness + metrics scrape. Returns False only when the
        replica is unreachable (a 503 /readyz is a HEALTHY not-ready
        answer). The metrics families parsed are exactly the router's
        inputs: the backlog gauge and the compile hit-rate counters."""
        t0 = time.monotonic()
        try:
            detail = http_json("GET", self.url + "/readyz",
                               timeout=timeout, ok=(200, 503))
        except Exception:
            return False
        self.ok_once = True
        self.ready = bool(detail.get("ready"))
        self.reasons = list(detail.get("reasons", ()))
        try:
            self._scrape_metrics(timeout)
        except Exception:
            pass                     # gauges go stale, probe still ok
        self.probe_seconds = time.monotonic() - t0
        return True

    def _scrape_metrics(self, timeout: float) -> None:
        # fault-injection point (runtime/faults.py `gw_scrape` site):
        # fires on the ReplicaSet PROBER thread — a `hang` parks only
        # the prober (routing continues on the last-probed gauges), a
        # `die` is absorbed as one failed scrape so the prober lives on
        # (tests/test_fleet_obs.py pins the isolation)
        try:
            faults.maybe_fail("gw_scrape")
        except SystemExit:
            return                   # gauges stale, prober survives
        families = obs_scrape.parse_exposition(
            http_text(self.url + "/metrics", timeout=timeout))
        self.queue_depth = obs_scrape.scalar(
            families, obs_scrape.QUEUE_DEPTH, self.queue_depth)
        self.backlog = obs_scrape.scalar(
            families, obs_scrape.BACKLOG, self.backlog)
        self.compile_count = obs_scrape.scalar(
            families, obs_scrape.COMPILE_COUNT, self.compile_count)
        self.compile_cache_hits = obs_scrape.scalar(
            families, obs_scrape.COMPILE_HITS,
            self.compile_cache_hits)
        self.resident_groups = obs_scrape.scalar(
            families, obs_scrape.RESIDENT_GROUPS, self.resident_groups)
        self.resident_bytes = obs_scrape.scalar(
            families, obs_scrape.RESIDENT_BYTES, self.resident_bytes)
        # the prober's incident scrape (tt-flight): when the replica's
        # dump counter advances — off the exposition this probe already
        # parsed — fetch the fresh bundle and cache it on the handle,
        # so a replica that dumps and then DIES still contributes its
        # last pre-death bundle to the gateway's stitched incident.
        # Same thread, same `gw_scrape` isolation contract as the rest
        # of this method: a failure leaves the previous cached copy.
        dumps = obs_scrape.scalar(families, obs_scrape.FLIGHT_DUMPS,
                                  self.flight_dumps)
        # the counter is per-incarnation: a restarted replica resets
        # to 0, so a BACKWARD reading means "new incarnation" and any
        # nonzero value there is a fresh bundle too (the respawn path
        # also resets our baseline, but a static replica restarted
        # behind our back only shows up here)
        if dumps > self.flight_dumps \
                or (dumps < self.flight_dumps and dumps > 0):
            try:
                self.last_incident = self.get_incident(timeout=timeout)
            except Exception:
                pass                 # keep the previous copy
        self.flight_dumps = dumps
        # the prober's tt-meter scrape: refresh the cached /v1/usage
        # ledger every probe round (the payload is bounded — active
        # jobs plus the TAIL_JOBS-retained terminals) so the gateway's
        # fleet aggregation, INCLUDING a dead replica's final
        # contribution, is never staler than one probe. Same thread
        # and isolation contract as the rest of this method: a failed
        # fetch (404 = metering off, timeouts, a mid-drain front)
        # leaves the previous cached copy in place.
        try:
            fresh = self.get_usage(timeout=timeout)
            if fresh is not None:
                self.note_usage(fresh)
        except Exception:
            pass                     # keep the previous copy

    def compile_hit_rate(self) -> float:
        total = self.compile_count + self.compile_cache_hits
        return self.compile_cache_hits / total if total > 0 else 0.0

    # -- verbs ----------------------------------------------------------

    def post_job(self, payload: dict, timeout: float = 5.0,
                 idempotent: bool = False, flow: int = 0,
                 resubmit: bool = False):
        # 409 (duplicate id) is SUCCESS only for a RESEND (failover
        # resubmission, or a retry whose first attempt landed but
        # lost its response): the job is already there, the placement
        # stands. On a job's very FIRST send a 409 is a genuine id
        # collision (e.g. a replica retaining a previous gateway
        # incarnation's job) and must surface as an error — silently
        # adopting the old job would hand the client someone else's
        # result.
        ok = (200, 202, 409) if idempotent else (200, 202)
        # the job's cross-process flow id (obs/spans.py XFLOW_BASE
        # range, minted by the gateway's tracer) rides a header, not
        # the payload: the payload is the replayable solve REQUEST and
        # must stay byte-stable across failover resends, while the
        # flow is pure telemetry
        headers = {}
        if flow:
            headers["X-TT-Flow"] = str(int(flow))
        if resubmit:
            # tt-meter: a resend of a job some replica ALREADY
            # ACCEPTED (failover replay/resume — the gateway keys this
            # on a previously successful placement, NOT on "a send was
            # attempted": a boot-window retry whose first POST never
            # landed must still be billed) must not re-count the job
            # in the new replica's tenant `jobs` ledger — the first
            # admission (possibly on a now-dead replica whose cached
            # ledger the gateway still sums) already did. Telemetry
            # like the flow header, so it rides a header, never the
            # byte-stable payload.
            headers["X-TT-Resubmit"] = "1"
        return http_json("POST", self.url + "/v1/solve", payload,
                         timeout=timeout, ok=ok,
                         headers=headers or None)

    def list_jobs(self, timeout: float = 5.0):
        """{id: {"state", ...}} for every job the replica knows —
        the bulk poll (GET /v1/jobs)."""
        return http_json("GET", f"{self.url}/v1/jobs",
                         timeout=timeout, ok=(200,)).get("jobs", {})

    def get_job(self, job_id: str, timeout: float = 5.0,
                with_records: bool = True, snapshot: bool = False):
        params = []
        if not with_records:
            params.append("records=0")
        if snapshot:
            # ?snapshot=1: the replica's latest park-fence ship unit
            # (wire snapshot + its exact record prefix) rides the view
            params.append("snapshot=1")
        suffix = "?" + "&".join(params) if params else ""
        return http_json(
            "GET",
            f"{self.url}/v1/jobs/{urllib.parse.quote(job_id)}"
            f"{suffix}",
            timeout=timeout, ok=(200,))

    def get_incident(self, timeout: float = 5.0):
        """GET /v1/incident: the replica's newest flight-recorder
        bundle (the inner `incident` object), or None before the first
        dump / without a recorder."""
        try:
            return http_json("GET", self.url + "/v1/incident",
                             timeout=timeout, ok=(200,)
                             ).get("incident")
        except FleetHTTPError as e:
            if e.status == 404:
                return None
            raise

    def get_usage(self, timeout: float = 5.0):
        """GET /v1/usage: the replica's tt-meter payload ({tenants,
        jobs} — obs/usage.py), or None when metering is off
        (--no-usage answers 404)."""
        try:
            return http_json("GET", self.url + "/v1/usage",
                             timeout=timeout, ok=(200,))
        except FleetHTTPError as e:
            if e.status == 404:
                return None
            raise

    def note_usage(self, fresh) -> None:
        """Cache a just-scraped `/v1/usage` payload (PROBER thread).
        BACKWARD-moving usage counters mean the replica is a fresh
        incarnation: a STATIC replica restarted behind our back has no
        respawn event for retire_usage to ride (the PR-14 documented
        gap), so the restart is detected HERE, by the counters
        themselves (obs/usage.progress — the flight-dump
        counter-baseline discipline), and the cached payload — the
        dead incarnation's final ledger — folds into `usage_base`
        before the fresh one replaces it. The bill survives external
        restarts too (tests/test_usage.py pins it)."""
        from timetabling_ga_tpu.obs import usage as obs_usage
        with self._usage_lock:
            if (self.last_usage is not None
                    and obs_usage.progress(fresh)
                    < obs_usage.progress(self.last_usage)):
                self.usage_base = (
                    self.last_usage if self.usage_base is None
                    else obs_usage.combine(
                        [self.usage_base, self.last_usage]))
            self.last_usage = fresh

    def usage_payload(self):
        """This handle's whole metered history: retired incarnations'
        folded ledgers (`usage_base`) + the live incarnation's latest
        scrape — what the gateway's fleet aggregation consumes. None
        when nothing was ever scraped. Reads the (base, last) pair
        under the lock: retire_usage moves a ledger between the two
        fields, and an unlocked reader catching it mid-move would
        bill a whole incarnation twice (or not at all)."""
        from timetabling_ga_tpu.obs import usage as obs_usage
        with self._usage_lock:
            base, last = self.usage_base, self.last_usage
        if base is None:
            return last
        if last is None:
            return base
        return obs_usage.combine([base, last])

    def retire_usage(self) -> None:
        """Fold the (about-to-die) incarnation's last-scraped ledger
        into the retired base — called by the prober right before a
        respawn, so the fresh worker's near-empty payload ADDS to the
        history instead of replacing it. One locked move, so
        usage_payload never sees the ledger in both fields."""
        from timetabling_ga_tpu.obs import usage as obs_usage
        with self._usage_lock:
            if self.last_usage is None:
                return
            self.usage_base = (
                self.last_usage if self.usage_base is None
                else obs_usage.combine([self.usage_base,
                                        self.last_usage]))
            self.last_usage = None

    def get_history(self, window: float | None = None,
                    timeout: float = 5.0):
        """GET /metrics/history[?window=S]: the replica's metrics
        history ring as JSON (obs/history.py window payload).
        window=0.0 means a zero-second window (empty series, like the
        endpoint itself), not 'everything'."""
        suffix = (f"?window={float(window)}" if window is not None
                  else "")
        return http_json("GET",
                         self.url + "/metrics/history" + suffix,
                         timeout=timeout, ok=(200,))

    def cancel_job(self, job_id: str, timeout: float = 5.0):
        return http_json(
            "DELETE",
            f"{self.url}/v1/jobs/{urllib.parse.quote(job_id)}",
            timeout=timeout, ok=(200, 202, 404, 409))

    def drain(self, timeout: float = 5.0, mode: str = "graceful"):
        suffix = f"?mode={mode}" if mode != "graceful" else ""
        return http_json("POST", self.url + "/v1/drain" + suffix, {},
                         timeout=timeout, ok=(200,))

    # -- process management --------------------------------------------

    def process_exited(self) -> bool:
        return self.proc is not None and self.proc.poll() is not None

    def terminate(self) -> None:
        if self.proc is None or self.proc.poll() is not None:
            return
        self.proc.terminate()
        try:
            self.proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            self.proc.kill()

    def view(self) -> dict:
        return {"name": self.name, "url": self.url,
                "ready": self.ready, "reasons": self.reasons,
                "dead": self.dead, "restarts": self.restarts,
                "queue_depth": self.queue_depth,
                "compile_hit_rate": round(self.compile_hit_rate(), 4)}


class ReplicaSet:
    """Probe-thread owner over a set of handles. Detects death
    (`dead_after` consecutive failed probes, or a reaped process),
    respawns spawned workers within `max_restarts`, and reports every
    death through `on_death(handle, respawned)` — the gateway's
    failover trigger. A restarted process comes back COLD (fresh
    compile caches, empty queue), so its jobs fail over exactly like
    a permanently dead replica's."""

    def __init__(self, handles, probe_every: float = 0.5,
                 probe_timeout: float = 2.0, dead_after: int = 3,
                 max_restarts: int = 0, on_death=None,
                 boot_grace: float = 120.0):
        self._handles = {h.name: h for h in handles}
        self.probe_every = probe_every
        self.probe_timeout = probe_timeout
        self.dead_after = dead_after
        self.max_restarts = max_restarts
        self.on_death = on_death
        self.boot_grace = boot_grace
        self._no_restart = False
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._probe_loop, name="tt-fleet-probe",
            daemon=True)

    # -- views ----------------------------------------------------------

    def all(self) -> list:
        return list(self._handles.values())

    def live(self) -> list:
        return [h for h in self._handles.values() if not h.dead]

    def get(self, name: str):
        return self._handles.get(name)

    def add(self, handle: ReplicaHandle) -> None:
        """Adopt a replica mid-run (the tt-scale autoscaler's scale-up
        seam): the prober picks it up on its next round, `--boot-grace`
        covers its jax import exactly like a startup spawn. A single
        dict store — the probe loop iterates over list() copies, so no
        lock is needed."""
        self._handles[handle.name] = handle

    # -- probing --------------------------------------------------------

    def start(self) -> "ReplicaSet":
        self._thread.start()
        return self

    def probe_all(self) -> None:
        for handle in list(self._handles.values()):
            if not handle.dead:
                self._probe_one(handle)
            elif (handle.respawn is None and handle.proc is None
                  and not handle.retired):
                # a STATIC (externally managed) replica keeps being
                # probed after death: a network blip that failed
                # dead_after probes must not remove a healthy process
                # from the fleet until the gateway restarts. It
                # rejoins COLD (its pins and warmth were dropped, its
                # jobs failed over) on the first answered probe. A
                # spawned worker's corpse, by contrast, stays dead —
                # its process is reaped, nothing can answer.
                if handle.probe(self.probe_timeout):
                    handle.dead = False
                    handle.fails = 0

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_every):
            self.probe_all()

    def _probe_one(self, handle: ReplicaHandle) -> None:
        exited = handle.process_exited()
        ok = False if exited else handle.probe(self.probe_timeout)
        if ok:
            handle.fails = 0
            return
        handle.ready = False
        if (not exited and not handle.ok_once
                and time.monotonic() - handle.born < self.boot_grace):
            # still booting (a spawned worker pays a long jax import
            # before it binds its port): unreachable is expected, not
            # a death — declaring it dead mid-boot would kill and
            # respawn it forever without one ever coming up
            return
        handle.fails += 1
        if exited or handle.fails >= self.dead_after:
            self._declare_dead(handle)

    def _declare_dead(self, handle: ReplicaHandle) -> None:
        respawned = False
        if (not self._no_restart and not handle.retired
                and handle.respawn is not None
                and handle.restarts < self.max_restarts):
            try:
                handle.terminate()   # reap a half-dead process first
                # the dying incarnation's metered work joins the
                # retired ledger BEFORE the fresh (near-empty) worker
                # starts answering /v1/usage — billing survives the
                # respawn like the flight-dump baseline reset below
                handle.retire_usage()
                handle.proc = handle.respawn()
                handle.restarts += 1
                handle.fails = 0
                handle.ok_once = False
                handle.born = time.monotonic()
                # fresh incarnation, fresh dump counter: without this
                # reset the new process's first bundles (counter 1, 2,
                # ...) would read as "below the old high-water" and
                # never be fetched (last_incident stays — the dead
                # incarnation's bundle IS the death's evidence until a
                # newer one lands)
                handle.flight_dumps = 0.0
                respawned = True
            except Exception:
                pass
        if not respawned:
            handle.dead = True
        if self.on_death is not None:
            self.on_death(handle, respawned)

    def stop_restarts(self) -> None:
        """Drain mode: replicas exiting after their drain are done,
        not dead — stop resurrecting them."""
        self._no_restart = True

    def close(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
        for handle in self._handles.values():
            handle.terminate()


# ------------------------------------------------------------- spawning


def free_port() -> int:
    """An ephemeral local port (bind-then-release; the worker rebinds
    it with SO_REUSEADDR a moment later)."""
    s = socket.socket()
    try:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
    finally:
        s.close()


def spawn_one(cfg: FleetConfig, name: str) -> ReplicaHandle:
    """One `tt serve --http` worker process on a fresh local port:
    the unit behind `--spawn N` startup AND the tt-scale autoscaler's
    scale-up actuation (fleet/autoscaler.py — the scaler thread is
    the only mid-run caller, TT608). The worker's record stream goes
    to ./tt-fleet-<name>.jsonl unless the passthrough serve flags
    already set -o; the respawn closure reuses the same port, so a
    restarted replica keeps its URL."""
    port = free_port()
    argv = [sys.executable, "-m", "timetabling_ga_tpu", "serve",
            "--http", f"127.0.0.1:{port}",
            "--backend", cfg.backend]
    if "-o" not in cfg.serve_args:
        argv += ["-o", f"tt-fleet-{name}.jsonl"]
    argv += list(cfg.serve_args)

    def respawn(argv=tuple(argv)):
        return subprocess.Popen(
            list(argv), stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)

    return ReplicaHandle(name, f"http://127.0.0.1:{port}",
                         proc=respawn(), respawn=respawn)


def spawn_local(cfg: FleetConfig) -> list:
    """`tt fleet --spawn N`: one `tt serve --http` worker per
    replica (spawn_one each)."""
    return [spawn_one(cfg, f"r{i}") for i in range(cfg.spawn)]


def in_process_replica(cfg: ServeConfig, name: str, now=None
                       ) -> tuple:
    """An in-process replica with a PRIVATE metrics registry (so N of
    them keep separate /readyz truths in one process) plus its
    gateway-side handle. cfg.http must be set (use '127.0.0.1:0').
    Records go to an in-memory buffer (tests read it back through
    `replica.tail._stream`) unless cfg.output names a file."""
    from timetabling_ga_tpu.obs.metrics import MetricsRegistry
    out = io.StringIO() if not cfg.output else None
    replica = Replica(cfg, name=name, out=out,
                      registry=MetricsRegistry(), now=now).start()
    return replica, ReplicaHandle(name, replica.url)
