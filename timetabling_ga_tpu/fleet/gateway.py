"""The fleet gateway: one HTTP solve front over N routed replicas.

Protocol (spoken IDENTICALLY by the gateway and by every replica's
`tt serve --http` front — fleet/replicas.py — so the router can treat
a replica as a one-member fleet):

  POST   /v1/solve      submit a job. Body: a raw `.tim` payload, or
                        JSON `{"tim": "...", "id": ..., "priority":
                        ..., "seed": ..., "generations": ...,
                        "deadline": ...}`, or pre-parsed problem JSON
                        (`{"problem": {...}}` — problem_from_json's
                        schema). Replies 202 `{"id": ...}` at once:
                        the job is ACCEPTED, not solved.
  GET    /v1/jobs/<id>  status + result + the job-tagged record tail
                        (the same JSONL records an unrouted solve
                        emits, demultiplexed per job).
  DELETE /v1/jobs/<id>  cancel, through the existing queue
                        cancellation path (serve/queue.py: immediate
                        for parked work, next control fence for
                        running work).
  POST   /v1/drain      graceful drain: admit nothing new, let parked
                        jobs finish, then shut down.
  GET    /v1/fleet      (gateway only) replica set, router stats,
                        job-state counts.
  GET    /metrics /healthz /readyz   the obs/http.py pull front, same
                        port — the router's scrape needs no second
                        listener.

Handler discipline (enforced by tt-analyze TT605): handlers ENQUEUE
and READ ONLY. A POST validates cheap text (the `.tim` header), drops
a command on the dispatcher's inbox, and returns; a GET serves the
cached job table. No handler ever does outbound I/O, touches a device,
or calls into a scheduler — ONE dispatcher thread owns every piece of
outbound HTTP (routing, submission, status polls, failover) and every
mutation of router state, so a scrape storm or a wedged handler can
never stall placement, and placement races cannot exist.

Failover: the ReplicaSet's prober declares a replica dead after
`--dead-after` consecutive failed probes (or a reaped worker process);
the dispatcher then forgets the dead replica's pins, discards its
unfinished jobs' partial record tails, and resubmits each job —
idempotent by job id, same payload, same seed — wherever the router
now places it. A job's record stream is a pure function of its own
(seed, chunk) lane RNG (serve/scheduler.py), so the replayed solve
emits records bit-identical to an unrouted solve of the same job
(tests/test_fleet.py and bench extra.fleet pin it, modulo timing
fields).
"""

from __future__ import annotations

import itertools
import json
import queue as queue_mod
import sys
import threading
import time
import urllib.parse

from timetabling_ga_tpu.obs import http as obs_http
from timetabling_ga_tpu.obs import metrics as obs_metrics
from timetabling_ga_tpu.problem import (
    DAYS_DEFAULT, SLOTS_PER_DAY_DEFAULT)
from timetabling_ga_tpu.runtime import faults
from timetabling_ga_tpu.runtime.config import (
    FleetConfig, ServeConfig, parse_fleet_args, parse_serve_args)
from timetabling_ga_tpu.runtime.retry import retry_transient
from timetabling_ga_tpu.serve.bucket import (
    BucketSpec, bucket_key_from_counts)
from timetabling_ga_tpu.fleet.router import NoReplicaError, Router

# request-body bound: the biggest committed ITC instance serializes to
# well under a megabyte; 32 MiB leaves room for dense problem JSON
# while keeping a lying Content-Length from ballooning a handler
MAX_BODY = 32 * 1024 * 1024

# terminal job states at the gateway (mirrors serve/queue.py JobState
# terminals plus the gateway-side 'rejected')
TERMINAL = ("done", "failed", "cancelled", "shed", "rejected")

_PAYLOAD_KEYS = ("id", "tim", "problem", "priority", "seed",
                 "generations", "deadline", "n_days", "slots_per_day")


# ---------------------------------------------------------------- protocol


def parse_solve_body(body: bytes) -> dict:
    """Canonical submit payload from a POST /v1/solve body: JSON when
    it parses as an object, else the whole body is a `.tim` text.
    Raises ValueError on anything unusable."""
    try:
        text = body.decode("utf-8")
    except UnicodeDecodeError as e:
        raise ValueError(f"body is not UTF-8: {e}") from None
    stripped = text.lstrip()
    if stripped.startswith("{"):
        try:
            obj = json.loads(text)
        except ValueError as e:
            raise ValueError(f"bad JSON body: {e}") from None
        payload = {k: obj[k] for k in _PAYLOAD_KEYS if k in obj}
        if "tim" not in payload and "problem" not in payload:
            raise ValueError(
                "JSON body needs a 'tim' text or a 'problem' object")
        return payload
    if not stripped:
        raise ValueError("empty body")
    return {"tim": text}


def payload_counts(payload: dict) -> tuple:
    """(E, R, F, S, n_days, slots_per_day) from a submit payload —
    `.tim` HEADER parse only (four ints off the first tokens), never
    the full instance: this runs on the gateway's routing path, where
    conflict matrices would be pure waste."""
    days = int(payload.get("n_days", DAYS_DEFAULT))
    slots = int(payload.get("slots_per_day", SLOTS_PER_DAY_DEFAULT))
    if "problem" in payload:
        p = payload["problem"]
        try:
            counts = tuple(int(p[k]) for k in (
                "n_events", "n_rooms", "n_features", "n_students"))
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"bad problem object: {e}") from None
        days = int(p.get("n_days", days))
        slots = int(p.get("slots_per_day", slots))
    else:
        # maxsplit: read ONLY the first four tokens — a dense instance
        # near the body cap must not be tokenized wholesale on the
        # handler thread
        toks = str(payload["tim"]).split(None, 4)[:4]
        if len(toks) < 4:
            raise ValueError(".tim header needs 4 counts "
                             "(events rooms features students)")
        try:
            counts = tuple(int(t) for t in toks)
        except ValueError:
            raise ValueError(
                f".tim header is not 4 ints: {toks}") from None
    if any(c < 0 for c in counts):
        raise ValueError(f"negative instance counts: {counts}")
    return counts + (days, slots)


# ---------------------------------------------------------------- handler


class ApiHandler(obs_http._Handler):
    """The `/v1` request router, shared by gateway and replica fronts.

    Extends the pull front's handler (GET /metrics //healthz //readyz
    keep working on the same port) with the solve API. TT605: every
    branch here bounds its socket reads by Content-Length and only
    calls the server's `api` object — whose entire surface enqueues
    commands or reads cached/queue state."""

    def do_GET(self):  # noqa: N802 (http.server's naming)
        path, _, query = self.path.partition("?")
        if path.startswith("/v1/jobs/"):
            params = dict(p.split("=", 1)
                          for p in query.split("&") if "=" in p)
            status, obj = self.server.api.job_view(
                self._job_id(path),
                with_records=params.get("records") != "0")
            self._reply_json(status, obj)
        elif path == "/v1/jobs":
            # bulk state-only view: the gateway's steady-state poll is
            # ONE of these per replica per tick, not one GET per job
            status, obj = self.server.api.jobs_view()
            self._reply_json(status, obj)
        elif path == "/v1/fleet":
            status, obj = self.server.api.fleet_view()
            self._reply_json(status, obj)
        else:
            super().do_GET()

    @staticmethod
    def _job_id(path: str) -> str:
        # clients QUOTE the id into the URL (ReplicaHandle, tt
        # submit); without the matching unquote here an id with a
        # space would 404 every poll — which _poll_jobs reads as
        # "replica lost the job" and fails over, forever
        return urllib.parse.unquote(path[len("/v1/jobs/"):])

    def do_POST(self):  # noqa: N802
        path, _, _ = self.path.partition("?")
        if path == "/v1/solve":
            body = self._body()
            if body is None:
                return
            try:
                payload = parse_solve_body(body)
            except ValueError as e:
                self._reply_json(400, {"error": str(e)[:300]})
                return
            status, obj = self.server.api.accept_solve(payload)
            self._reply_json(status, obj)
        elif path == "/v1/drain":
            # consume any declared body BEFORE the 200: a keep-alive
            # client's next request must not be parsed out of the
            # leftover payload bytes (the >=400 path closes the
            # connection instead — _reply)
            self._discard_body()
            status, obj = self.server.api.accept_drain()
            self._reply_json(status, obj)
        else:
            self._reply_json(404, {"error": f"no route {path!r}"})

    def do_DELETE(self):  # noqa: N802
        path, _, _ = self.path.partition("?")
        if path.startswith("/v1/jobs/"):
            status, obj = self.server.api.accept_cancel(
                self._job_id(path))
            self._reply_json(status, obj)
        else:
            self._reply_json(404, {"error": f"no route {path!r}"})

    def _discard_body(self) -> None:
        try:
            n = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            n = 0
        if 0 < n <= MAX_BODY:
            self.rfile.read(n)
        elif n > MAX_BODY:
            self.close_connection = True

    def _reply(self, status: int, body: bytes, ctype: str) -> None:
        # error replies may leave an unread request body in the
        # socket (411/413 before the read, POST routes that never
        # read): close the connection rather than let a keep-alive
        # client parse its next request out of the leftover bytes
        if status >= 400:
            self.close_connection = True
        super()._reply(status, body, ctype)

    def _body(self):
        """Content-Length-bounded body read; replies and returns None
        on anything else. An unbounded `rfile.read()` would park this
        handler thread until the client hangs up — exactly the read
        TT605 bans."""
        n = self.headers.get("Content-Length")
        if n is None:
            self._reply_json(411, {"error": "Content-Length required"})
            return None
        try:
            n = int(n)
        except ValueError:
            self._reply_json(400, {"error": "bad Content-Length"})
            return None
        if n < 0 or n > MAX_BODY:
            self._reply_json(
                413, {"error": f"body over {MAX_BODY} bytes"})
            return None
        return self.rfile.read(n)


# ---------------------------------------------------------------- gateway


class GatewayJob:
    """One job's gateway-side life: payload kept for failover replay,
    state/result/records mirrored from the owning replica by the
    dispatcher's polls (handlers read ONLY this cache)."""

    def __init__(self, job_id: str, payload: dict, now: float):
        self.id = job_id
        self.payload = payload
        self.counts = None           # payload_counts result
        self.bucket = None
        self.replica = None          # owning replica name
        self.state = "accepted"
        self.result = None
        self.error = None
        self.records: list = []
        self.records_final = False
        self.records_truncated = False   # tail lost records (over-cap
        #                                  ring, or a settle fallback)
        #                                  — identity cannot hold
        self.extra_polls = 0         # terminal-tail settle budget
        self.place_attempts = 0
        self.place_started = None    # current placement round's epoch:
        #                              reset by failover, so a job that
        #                              ran for hours still gets the
        #                              full --place-timeout to wait
        #                              out a respawning replica
        self.cancel_requested = False
        self.sent_any = False        # some send of this payload may
        #                              have reached a replica: later
        #                              sends are idempotent resends
        #                              (409 = already placed)
        self.submitted_t = now
        self.finished_t = None
        self.counted = False         # terminal counters bumped once

    def terminal(self) -> bool:
        return self.state in TERMINAL

    def view(self, with_records: bool = True) -> dict:
        out = {"id": self.id, "state": self.state,
               "replica": self.replica,
               "bucket": list(self.bucket) if self.bucket else None,
               "result": self.result, "error": self.error}
        if with_records:
            out["records"] = list(self.records)
            out["records_truncated"] = self.records_truncated
        return out


class GatewayApi:
    """The handlers' surface: enqueue-or-read-only over the Gateway
    (TT605 — no outbound I/O, no device, no registry mutation)."""

    def __init__(self, gw: "Gateway"):
        self._gw = gw

    def accept_solve(self, payload: dict):
        gw = self._gw
        if gw.draining:
            return 503, {"error": "draining", "reasons": ["draining"]}
        try:
            counts = payload_counts(payload)
        except ValueError as e:
            return 400, {"error": str(e)[:300]}
        with gw.jobs_lock:
            job_id = payload.get("id")
            if job_id is None:
                # auto-ids skip anything a client already claimed —
                # an id-less submission must never be rejected for a
                # collision it did not cause
                job_id = f"gw-{next(gw.auto_id)}"
                while job_id in gw.jobs:
                    job_id = f"gw-{next(gw.auto_id)}"
            job_id = str(job_id)
            if job_id in gw.jobs:
                return 409, {"error": "duplicate job id", "id": job_id,
                             "state": gw.jobs[job_id].state}
            active = sum(1 for j in gw.jobs.values()
                         if not j.terminal())
            if active >= gw.cfg.backlog:
                return 429, {"error": f"gateway backlog full "
                                      f"({gw.cfg.backlog} active)"}
            job = GatewayJob(job_id, dict(payload, id=job_id),
                             gw.now())
            job.counts = counts
            gw.jobs[job_id] = job
        gw.inbox.put(("submit", job_id))
        return 202, {"id": job_id, "state": "accepted"}

    def job_view(self, job_id: str, with_records: bool = True):
        with self._gw.jobs_lock:
            job = self._gw.jobs.get(job_id)
            if job is None:
                return 404, {"error": f"unknown job {job_id!r}"}
            return 200, job.view(with_records=with_records)

    def jobs_view(self):
        """Bulk state-only view (protocol parity with the replica
        front — a meta-gateway could poll this gateway the same
        way)."""
        with self._gw.jobs_lock:
            return 200, {"jobs": {j.id: {"state": j.state,
                                         "replica": j.replica}
                                  for j in self._gw.jobs.values()}}

    def accept_cancel(self, job_id: str):
        gw = self._gw
        with gw.jobs_lock:
            job = gw.jobs.get(job_id)
            if job is None:
                return 404, {"error": f"unknown job {job_id!r}"}
            if job.terminal():
                return 409, {"id": job_id, "state": job.state,
                             "error": "already terminal"}
        gw.inbox.put(("cancel", job_id))
        return 202, {"id": job_id, "cancelling": True}

    def accept_drain(self):
        gw = self._gw
        gw.draining = True
        gw.inbox.put(("drain",))
        with gw.jobs_lock:
            active = sum(1 for j in gw.jobs.values()
                         if not j.terminal())
        return 200, {"draining": True, "active": active}

    def fleet_view(self):
        gw = self._gw
        with gw.jobs_lock:
            states: dict = {}
            for j in gw.jobs.values():
                states[j.state] = states.get(j.state, 0) + 1
        return 200, {"replicas": [h.view()
                                  for h in gw.replicas.all()],
                     "router": gw.router.stats(),
                     "jobs": states, "draining": gw.draining}


class Gateway:
    """The fleet front: HTTP API + single-threaded dispatcher that
    owns routing, submission, polling, failover, and drain."""

    def __init__(self, cfg: FleetConfig, handles, owned: bool = False,
                 now=None):
        # deterministic fault injection, mirroring SolveService: the
        # gateway/route sites fire under `tt fleet` too
        spec = faults.active_spec(cfg.faults)
        if spec:
            faults.install(spec)
        self.cfg = cfg
        self.now = now or time.monotonic
        self.owned = owned           # gateway manages replica lifetime
        self.draining = False
        self.drained = threading.Event()
        self.jobs: dict = {}
        self.jobs_lock = threading.RLock()
        self.auto_id = itertools.count(1)
        self.inbox = queue_mod.Queue()
        self._requeue: list = []     # placement retries, drained ONCE
        #                              per poll tick (an inbox requeue
        #                              would be popped right back and
        #                              starve the poll/drain phases)
        self._terminal_order: list = []   # settled ids, eviction FIFO
        # the serve flags spawned workers run with double as the
        # router's bucket spec — one parse, no drift
        serve_cfg = (parse_serve_args(cfg.serve_args)
                     if cfg.serve_args else ServeConfig())
        self.spec = BucketSpec(
            event_floor=serve_cfg.bucket_events,
            room_floor=serve_cfg.bucket_rooms,
            feature_floor=serve_cfg.bucket_features,
            student_floor=serve_cfg.bucket_students,
            ratio=serve_cfg.bucket_ratio)
        from timetabling_ga_tpu.fleet.replicas import ReplicaSet
        self.replicas = ReplicaSet(
            handles, probe_every=cfg.probe_every,
            probe_timeout=cfg.probe_timeout,
            dead_after=cfg.dead_after, max_restarts=cfg.max_restarts,
            on_death=self._on_death, boot_grace=cfg.boot_grace)
        self.router = Router(self.replicas)
        self.registry = obs_metrics.MetricsRegistry()
        self.registry.gauge_fn(
            "fleet.replicas_ready",
            lambda: sum(1 for h in self.replicas.live() if h.ready))
        self.registry.gauge_fn(
            "serve.queue_depth",
            lambda: sum(1 for j in list(self.jobs.values())
                        if not j.terminal()))
        self.registry.gauge("serve.backlog").set(cfg.backlog)
        self._thread = threading.Thread(
            target=self._dispatch_loop, name="tt-fleet-dispatch",
            daemon=True)
        self.front = obs_http.ObsServer(
            cfg.listen, registry=self.registry,
            probes={"dispatcher": self._thread.is_alive},
            handler=ApiHandler, api=GatewayApi(self), site="gateway")

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "Gateway":
        # one synchronous probe round before anything routes: the
        # router's first decision should see real readiness, not the
        # all-unprobed default
        self.replicas.probe_all()
        self.replicas.start()
        self.front.start()
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return self.front.url

    def request_drain(self) -> None:
        self.draining = True
        self.inbox.put(("drain",))

    def close(self) -> None:
        self._stop = True
        self.inbox.put(("wake",))
        self._thread.join(timeout=5.0)
        self.front.close()
        self.replicas.close()

    # -- the dispatcher thread: ALL outbound I/O lives here -------------

    _stop = False

    def _dispatch_loop(self) -> None:
        try:
            while not self._stop:
                try:
                    cmd = self.inbox.get(timeout=self.cfg.poll_every)
                except queue_mod.Empty:
                    cmd = None
                while cmd is not None:
                    self._handle(cmd)
                    try:
                        cmd = self.inbox.get_nowait()
                    except queue_mod.Empty:
                        cmd = None
                self._poll_jobs()
                # deferred placement retries AFTER the poll phase, one
                # round per tick: a replica paying its boot-time jax
                # import must not starve status polls or drain progress
                retries, self._requeue = self._requeue, []
                for job_id in retries:
                    self._handle(("submit", job_id))
                self._drain_tick()
        except SystemExit:
            # injected `route`/`gateway` die: ends THIS thread only —
            # /healthz's dispatcher probe goes false, replicas run on
            return

    def _handle(self, cmd: tuple) -> None:
        kind = cmd[0]
        if kind == "submit":
            with self.jobs_lock:
                job = self.jobs.get(cmd[1])
            if job is not None and not job.terminal():
                if job.cancel_requested:
                    # cancelled while waiting for placement: settle
                    # locally, nothing to route
                    job.state = "cancelled"
                    self._settle(job)
                    return
                if job.place_attempts == 0:   # not a requeue retry
                    self.registry.counter("fleet.jobs_accepted").inc()
                if job.place_started is None:
                    job.place_started = self.now()
                self._place(job)
        elif kind == "cancel":
            self._cancel(cmd[1])
        elif kind == "drain":
            self.registry.gauge("serve.draining").set(1.0)
        elif kind == "failover":
            self._failover(cmd[1])
        # "wake" and anything else: just a loop tick

    def _place(self, job: GatewayJob, exclude: tuple = ()) -> None:
        """Route + submit one job, failing over across replicas until
        placed or nothing remains."""
        try:
            job.bucket = bucket_key_from_counts(*job.counts,
                                                spec=self.spec)
            handle = self.router.route(job.bucket, exclude=exclude)
        except NoReplicaError as e:
            self._fail(job, str(e))
            return
        except faults.FaultInjected as e:
            self._fail(job, f"routing fault: {e}")
            return
        job.place_attempts += 1

        def send():
            # DATA-plane timeout: the payload can be a multi-MB
            # problem JSON; the 2 s probe budget is for gauges.
            # Any attempt after the first is an idempotent RESEND
            # (the earlier one may have landed and lost its reply) —
            # only then is a replica's 409 'already have it' success.
            idem = job.sent_any
            job.sent_any = True
            return handle.post_job(job.payload,
                                   timeout=self.cfg.io_timeout,
                                   idempotent=idem)

        try:
            retry_transient(send, attempts=self.cfg.route_retries,
                            wait_s=self.cfg.retry_wait_s, backoff=2.0,
                            max_wait_s=2.0)
        except Exception as e:
            from timetabling_ga_tpu.runtime.retry import is_transient
            started = (job.place_started if job.place_started
                       is not None else self.now())
            if (is_transient(e) and self.now() - started
                    < self.cfg.place_timeout):
                # a replica still booting or mid-restart: requeue —
                # retried once per poll tick (the deferred list, not
                # the inbox) rather than burning the exclusion list on
                # a process that is paying its jax import (a spawned
                # worker takes many seconds before it binds its port).
                # The window is anchored at THIS placement round, so
                # failover after a long run gets the full budget.
                self._requeue.append(job.id)
                return
            remaining = [h for h in self.replicas.live()
                         if h.name not in exclude
                         and h.name != handle.name]
            if remaining:
                self._place(job, exclude + (handle.name,))
            else:
                self._fail(job, f"no replica accepted job: "
                                f"{str(e)[:200]}")
            return
        job.replica = handle.name
        job.state = "routed"
        self.registry.counter("fleet.jobs_routed").inc()

    def _cancel(self, job_id: str) -> None:
        with self.jobs_lock:
            job = self.jobs.get(job_id)
        if job is None or job.terminal():
            return
        # remembered across failover: a job cancelled while its
        # replica is dying must NOT be resubmitted and solved to
        # completion — _failover and the requeue path check this flag
        job.cancel_requested = True
        if job.replica is None:
            job.state = "cancelled"
            self._settle(job)
            return
        handle = self.replicas.get(job.replica)
        if handle is not None:
            try:
                handle.cancel_job(job.id,
                                  timeout=self.cfg.probe_timeout)
            except Exception:
                pass           # polls (or failover) settle the state

    def _poll_jobs(self) -> None:
        """Refresh the cached job table from the owning replicas —
        the ONLY place replica job state enters the gateway. The
        steady-state poll is STATE-ONLY (`?records=0` — a long job's
        tail would otherwise be re-serialized on every tick); the
        record tail is fetched once the job turns terminal, and the
        job settles when that tail carries the terminal jobEntry (the
        replica's AsyncWriter drains asynchronously, so state can
        lead the records by a beat). An over-cap ring tail or an
        exhausted settle budget settles with `records_truncated`
        marked — visible, never a silently frozen partial stream."""
        with self.jobs_lock:
            jobs = [j for j in self.jobs.values()
                    if j.replica is not None
                    and not (j.terminal() and j.records_final)]
        by_replica: dict = {}
        for job in jobs:
            by_replica.setdefault(job.replica, []).append(job)
        for name, group in by_replica.items():
            handle = self.replicas.get(name)
            if handle is None or handle.dead:
                continue           # prober + failover own this case
            try:
                states = handle.list_jobs(
                    timeout=self.cfg.probe_timeout)
            except Exception:
                continue           # prober decides life and death
            for job in group:
                info = states.get(job.id)
                if info is None:
                    # a LIVE replica that does not know the job: it
                    # restarted inside the dead_after window and lost
                    # its state — per-job failover, because the
                    # prober sees a healthy process and will never
                    # declare it dead
                    self._reassign(job)
                    continue
                state = info.get("state")
                if not state or state not in TERMINAL:
                    if state:
                        job.state = state
                    continue
                # the replica reports terminal — but the gateway view
                # must not SAY so until the record tail is cached, or
                # a fast client reads `done` with an empty stream;
                # state and records publish together at settle
                try:
                    full = handle.get_job(
                        job.id, timeout=self.cfg.io_timeout)
                except Exception:
                    continue
                job.result = full.get("result", job.result)
                job.error = full.get("error", job.error)
                records = full.get("records") or []
                complete = any(
                    rec.get("jobEntry", {}).get("event") in TERMINAL
                    for rec in records)
                truncated = bool(full.get("records_truncated"))
                job.extra_polls += 1
                if complete or truncated or job.extra_polls >= 50:
                    job.records = records
                    job.state = state
                    job.records_truncated = truncated or not complete
                    self._settle(job)

    def _on_death(self, handle, respawned: bool) -> None:
        """ReplicaSet prober callback (PROBER thread): only enqueue —
        router/job state is touched exclusively on the dispatcher.
        A respawned worker comes back cold, so its jobs fail over
        exactly like a dead one's (the handle stays live and may win
        them back)."""
        self.inbox.put(("failover", handle.name))

    def _failover(self, name: str) -> None:
        """A replica died (prober callback, via the inbox — so router
        state is only ever touched on this thread): forget its pins
        and warmth, then resubmit every unfinished job it owned.
        Idempotent by job id: the payload (id, seed, generation
        budget) replays verbatim, partial record tails are discarded,
        and the fresh solve's stream replaces them wholesale — the
        client observes exactly one completion with exactly one record
        stream. A job that COMPLETED on the dead replica but whose
        records the polls had not finished caching is replayed too:
        the stream is a pure function of the job, so the replay emits
        the identical records the lost copy held."""
        self.router.on_replica_dead(name)
        with self.jobs_lock:
            victims = [j for j in self.jobs.values()
                       if j.replica == name
                       and not (j.terminal() and j.records_final)]
        for job in victims:
            self._reassign(job)

    def _reassign(self, job: GatewayJob) -> None:
        """One job's failover: discard the lost copy's partial
        records and replay the payload through a fresh routing — or
        honor a pending cancel (the replica that would have solved
        the rest is gone anyway)."""
        if job.cancel_requested:
            job.state = "cancelled"
            self._settle(job)
            return
        job.records = []
        job.records_final = False
        job.records_truncated = False
        job.replica = None
        job.state = "accepted"
        job.extra_polls = 0
        job.place_started = self.now()       # fresh placement budget
        self.registry.counter("fleet.jobs_failed_over").inc()
        self._place(job)

    def _settle(self, job: GatewayJob) -> None:
        """A job is terminal AND its records are cached: final
        accounting, then retention — the payload (the whole `.tim`
        text, kept only for failover replay) is released, and settled
        jobs beyond `--retain-terminal` are evicted oldest-first (a
        long-running gateway must not hold every instance it ever
        served; an evicted id answers 404)."""
        job.records_final = True
        if job.finished_t is None:
            job.finished_t = self.now()
        job.payload = None
        job.counts = None
        if not job.counted:
            job.counted = True
            name = ("fleet.jobs_done" if job.state == "done"
                    else "fleet.jobs_failed")
            self.registry.counter(name).inc()
            self.registry.histogram("fleet.job_seconds").observe(
                job.finished_t - job.submitted_t,
                exemplar={"job": job.id})
        self._terminal_order.append(job.id)
        while len(self._terminal_order) > self.cfg.retain_terminal:
            evicted = self._terminal_order.pop(0)
            with self.jobs_lock:
                self.jobs.pop(evicted, None)

    def _fail(self, job: GatewayJob, reason: str) -> None:
        job.state = "failed"
        job.error = reason
        self._settle(job)

    def _drain_tick(self) -> None:
        if not self.draining or self.drained.is_set():
            return
        with self.jobs_lock:
            active = [j for j in self.jobs.values()
                      if not (j.terminal() and j.records_final)]
        if active or not self.inbox.empty():
            return
        # every job settled AND its records are cached — only now may
        # owned replicas drain (they exit after draining; a replica
        # that exits before the gateway cached its tails would lose
        # them)
        if self.owned:
            self.replicas.stop_restarts()
            for handle in self.replicas.live():
                try:
                    handle.drain(timeout=self.cfg.probe_timeout)
                except Exception:
                    pass
        self.drained.set()


# ---------------------------------------------------------------- CLI


def main_fleet(argv) -> int:
    """`tt fleet` entry point (cli.py dispatches here). Runs until a
    POST /v1/drain (or SIGTERM/SIGINT, mapped to the same drain)
    completes."""
    import signal

    cfg = parse_fleet_args(argv)
    from timetabling_ga_tpu.fleet import replicas as replicas_mod
    if cfg.spawn:
        handles = replicas_mod.spawn_local(cfg)
    else:
        handles = [replicas_mod.ReplicaHandle(f"r{i}", url)
                   for i, url in enumerate(cfg.replicas)]
    gw = Gateway(cfg, handles, owned=bool(cfg.spawn))
    gw.start()
    print(f"# tt fleet: gateway on {gw.url} fronting "
          f"{len(handles)} replica(s): "
          f"{', '.join(h.url for h in handles)}",
          file=sys.stderr, flush=True)

    def _drain(signum, frame):
        print("# tt fleet: drain requested", file=sys.stderr,
              flush=True)
        gw.request_drain()

    signal.signal(signal.SIGTERM, _drain)
    signal.signal(signal.SIGINT, _drain)
    try:
        while not gw.drained.wait(0.5):
            pass
    finally:
        gw.close()
    return 0
