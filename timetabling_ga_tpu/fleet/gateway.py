"""The fleet gateway: one HTTP solve front over N routed replicas.

Protocol (spoken IDENTICALLY by the gateway and by every replica's
`tt serve --http` front — fleet/replicas.py — so the router can treat
a replica as a one-member fleet):

  POST   /v1/solve      submit a job. Body: a raw `.tim` payload, or
                        JSON `{"tim": "...", "id": ..., "priority":
                        ..., "seed": ..., "generations": ...,
                        "deadline": ...}`, or pre-parsed problem JSON
                        (`{"problem": {...}}` — problem_from_json's
                        schema). Replies 202 `{"id": ...}` at once:
                        the job is ACCEPTED, not solved.
  GET    /v1/jobs/<id>  status + result + the job-tagged record tail
                        (the same JSONL records an unrouted solve
                        emits, demultiplexed per job).
  DELETE /v1/jobs/<id>  cancel, through the existing queue
                        cancellation path (serve/queue.py: immediate
                        for parked work, next control fence for
                        running work).
  POST   /v1/drain      graceful drain: admit nothing new, let parked
                        jobs finish, then shut down.
  GET    /v1/fleet      (gateway only) replica set, router stats,
                        job-state counts.
  GET    /metrics /healthz /readyz   the obs/http.py pull front, same
                        port — the router's scrape needs no second
                        listener.

Handler discipline (enforced by tt-analyze TT605): handlers ENQUEUE
and READ ONLY. A POST validates cheap text (the `.tim` header), drops
a command on the dispatcher's inbox, and returns; a GET serves the
cached job table. No handler ever does outbound I/O, touches a device,
or calls into a scheduler — ONE dispatcher thread owns every piece of
outbound HTTP (routing, submission, status polls, failover) and every
mutation of router state, so a scrape storm or a wedged handler can
never stall placement, and placement races cannot exist.

Failover — RESUME, don't replay (README "Fleet resume"): the
ReplicaSet's prober declares a replica dead after `--dead-after`
consecutive failed probes (or a reaped worker process); the dispatcher
then forgets the dead replica's pins and resubmits each unfinished job
wherever the router now places it. Under `--snapshot-hwm` (default on)
the dispatcher has been CACHING each in-flight job's latest park-fence
snapshot (`?snapshot=1`, published by the owning replica at every
quantum park; fingerprint-validated stdlib-only via
serve/snapshot.verify_wire), so the resubmission carries the wire
snapshot: the survivor admits the job already PARKED at the shipped
progress — at most one quantum re-runs, never hours — and the shipped
record prefix joins the gateway's accumulated `prefix` so the settled
stream is whole, duplicate-free (the restored `emitted` floor), and
identical to an unrouted solve modulo timing/fault records
(tests/test_resume.py pins it). Jobs whose snapshot was never cached,
was evicted (oldest-progress-first under the byte budget —
`fleet.resume.evictions`), or failed validation fall back to the
replay failover, exactly as before: idempotent by job id, same
payload, same seed, records bit-identical to an unrouted solve. The
resume story is on /metrics as `fleet.resume.{hits,replays,fetches,
fetch_errors,rejected,evictions,demoted}` + `fleet.resume.{bytes,
cached}` gauges (`demoted` = the replica refused an attached snapshot
and replayed; the gateway detects the fresh stream by its `admitted`
jobEntry and drops the now-redundant prefix). `POST /v1/drain?mode=preempt&replica=NAME` (and SIGTERM on a
`--preempt-on-term` spot worker) is the cooperative form: the replica
parks + ships everything within `--preempt-grace` and its jobs resume
elsewhere — lossless scale-down.

Observability (tt-obs v5, README "Fleet observability"): `-o LOG`
gives the gateway its own JSONL telemetry stream through an
AsyncWriter (fault site `gw_writer` — a dead log writer disables
emission and the dispatcher routes on, never stalls). The dispatcher's
phases emit spans (route / submit / poll / failover / settle, plus a
`routed` span measuring admit→placed), every placement emits a
`routeEntry` (bucket, chosen replica, score inputs, hit/warm/miss),
and metricsEntry snapshots ride every `--metrics-every` ticks. Each
admitted job gets a CROSS-PROCESS flow id (obs/spans.py XFLOW_BASE
range) shipped to its replica as an `X-TT-Flow` header, so
`tt trace --job ID gateway.jsonl replica*.jsonl` stitches the job's
whole life — gateway routing leg + replica solve leg — into one
Perfetto timeline with process-labeled lanes and flow arrows crossing
the process boundary.

/metrics parity: everything `/v1/fleet` reports is derivable from the
gateway's registry families on the same port — per-replica
`fleet.replica.<name>.{ready,backlog,probe_seconds,compile_hit_rate,
pins,restarts}` gauges, routing counters `fleet.route.{hit,warm,miss,
repins}`, `fleet.jobs_{accepted,routed,done,failed,failed_over}`,
dispatcher `fleet.tick_seconds` timing, `fleet.submit_retries`, and
the `fleet.job_seconds` e2e histogram with job-id exemplars. The JSON
view is a convenience snapshot (refreshed once per dispatcher tick,
served from a lock-guarded copy — handlers never read router state
the dispatcher is mutating); dashboards should scrape `/metrics`.

Readiness for HA stacking: the gateway answers `/readyz` under the
same pinned JSON contract as replicas (obs/http.py readiness), with
gateway reasons `no_ready_replica`, `dispatcher_stalled` (watchdog
over the dispatcher's tick age, `--stall-after`) and `slo_burn`
(`--slo-p99` rolling-window p99 over e2e latencies; the burn's
start/clear also emit faultEntry records on the gateway log).
"""

from __future__ import annotations

import collections
import itertools
import json
import queue as queue_mod
import sys
import threading
import time
import urllib.parse

from timetabling_ga_tpu.obs import http as obs_http
from timetabling_ga_tpu.obs import metrics as obs_metrics
from timetabling_ga_tpu.obs.spans import NULL_TRACER, SpanTracer, XFLOW_BASE
from timetabling_ga_tpu.problem import (
    DAYS_DEFAULT, SLOTS_PER_DAY_DEFAULT)
from timetabling_ga_tpu.runtime import faults, jsonl
from timetabling_ga_tpu.runtime.config import (
    FleetConfig, ServeConfig, parse_fleet_args, parse_serve_args)
from timetabling_ga_tpu.runtime.retry import retry_transient
from timetabling_ga_tpu.serve import snapshot as snapshot_mod
from timetabling_ga_tpu.serve.bucket import (
    BucketSpec, bucket_key_from_counts)
from timetabling_ga_tpu.fleet.router import NoReplicaError, Router

# request-body bound: the biggest committed ITC instance serializes to
# well under a megabyte; 32 MiB leaves room for dense problem JSON
# while keeping a lying Content-Length from ballooning a handler
MAX_BODY = 32 * 1024 * 1024

# terminal job states at the gateway (mirrors serve/queue.py JobState
# terminals plus the gateway-side 'rejected')
TERMINAL = ("done", "failed", "cancelled", "shed", "rejected")

_PAYLOAD_KEYS = ("id", "tim", "problem", "priority", "seed",
                 "generations", "deadline", "n_days", "slots_per_day",
                 # a warm-start wire snapshot (serve/snapshot.py): the
                 # gateway attaches one at resume-on-failover, and a
                 # client may submit one directly (incremental
                 # re-solve warm starts ride the same seam)
                 "snapshot",
                 # tt-edit (serve/editsolve.py; README "Incremental
                 # re-solve"): an edit spec {"base": <job id or inline
                 # instance>, "ops"|"edited": ..., "w_anchor": W}. The
                 # gateway resolves a job-id base into the base
                 # payload + its cached/fetched snapshot on the
                 # dispatcher (_resolve_edit) before routing; the
                 # REPLICA applies the edit, attaches the anchored
                 # objective, and transplants the population —
                 # diff/apply never run on the gateway (stdlib-only
                 # discipline)
                 "edit",
                 # tt-meter (obs/usage.py): the tenant tag rides the
                 # payload end to end — tt submit --tenant → gateway →
                 # replica → Job.tenant — so capacity attribution
                 # survives routing AND failover (the replayed payload
                 # is byte-stable, tenant included)
                 "tenant")


# ---------------------------------------------------------------- protocol


def parse_solve_body(body: bytes) -> dict:
    """Canonical submit payload from a POST /v1/solve body: JSON when
    it parses as an object, else the whole body is a `.tim` text.
    Raises ValueError on anything unusable."""
    try:
        text = body.decode("utf-8")
    except UnicodeDecodeError as e:
        raise ValueError(f"body is not UTF-8: {e}") from None
    stripped = text.lstrip()
    if stripped.startswith("{"):
        try:
            obj = json.loads(text)
        except ValueError as e:
            raise ValueError(f"bad JSON body: {e}") from None
        payload = {k: obj[k] for k in _PAYLOAD_KEYS if k in obj}
        if ("tim" not in payload and "problem" not in payload
                and "edit" not in payload):
            raise ValueError(
                "JSON body needs a 'tim' text, a 'problem' object, "
                "or an 'edit' spec")
        return payload
    if not stripped:
        raise ValueError("empty body")
    return {"tim": text}


def payload_counts(payload: dict) -> tuple:
    """(E, R, F, S, n_days, slots_per_day) from a submit payload —
    `.tim` HEADER parse only (four ints off the first tokens), never
    the full instance: this runs on the gateway's routing path, where
    conflict matrices would be pure waste."""
    days = int(payload.get("n_days", DAYS_DEFAULT))
    slots = int(payload.get("slots_per_day", SLOTS_PER_DAY_DEFAULT))
    if "edit" in payload and "tim" not in payload \
            and "problem" not in payload:
        return edit_payload_counts(payload)
    if "problem" in payload:
        p = payload["problem"]
        try:
            counts = tuple(int(p[k]) for k in (
                "n_events", "n_rooms", "n_features", "n_students"))
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"bad problem object: {e}") from None
        days = int(p.get("n_days", days))
        slots = int(p.get("slots_per_day", slots))
    else:
        # maxsplit: read ONLY the first four tokens — a dense instance
        # near the body cap must not be tokenized wholesale on the
        # handler thread
        toks = str(payload["tim"]).split(None, 4)[:4]
        if len(toks) < 4:
            raise ValueError(".tim header needs 4 counts "
                             "(events rooms features students)")
        try:
            counts = tuple(int(t) for t in toks)
        except ValueError:
            raise ValueError(
                f".tim header is not 4 ints: {toks}") from None
    if any(c < 0 for c in counts):
        raise ValueError(f"negative instance counts: {counts}")
    return counts + (days, slots)


def edit_payload_counts(payload: dict):
    """(E, R, F, S, n_days, slots_per_day) for an EDIT payload, or
    None when routing counts must wait for the dispatcher to resolve
    a job-id base (`_resolve_edit` — the handler thread must not read
    the job table). Header-only arithmetic, stdlib throughout: an
    inline 'edited' instance counts like any submit payload; an
    inline base counts + per-op event deltas (only add_event /
    remove_event change any routed dimension). Malformed specs raise
    ValueError like every other bad payload."""
    edit = payload.get("edit")
    if not isinstance(edit, dict):
        raise ValueError("'edit' must be an object")
    if "base" not in edit:
        raise ValueError("edit spec needs a 'base'")
    if ("ops" in edit) == ("edited" in edit):
        raise ValueError(
            "edit spec needs exactly one of 'ops' or 'edited'")
    carry = {k: payload[k] for k in ("n_days", "slots_per_day")
             if k in payload}
    if "edited" in edit:
        edited = edit["edited"]
        if not isinstance(edited, dict) or (
                "tim" not in edited and "problem" not in edited):
            raise ValueError("edit 'edited' needs a 'tim' text or a "
                             "'problem' object")
        return payload_counts({**carry, **edited})
    ops = edit["ops"]
    if not isinstance(ops, (list, tuple)):
        raise ValueError("edit 'ops' must be a list")
    base = edit["base"]
    if isinstance(base, str):
        return None                     # deferred: dispatcher resolves
    if not isinstance(base, dict) or (
            "tim" not in base and "problem" not in base):
        raise ValueError("edit base needs a job id, a 'tim' text, or "
                         "a 'problem' object")
    e, r, f, s, days, slots = payload_counts({**carry, **base})
    for op in ops:
        kind = op.get("op") if isinstance(op, dict) else None
        if kind == "add_event":
            e += 1
        elif kind == "remove_event":
            e -= 1
    if e <= 0:
        raise ValueError("edit removes every event")
    return (e, r, f, s, days, slots)


# ---------------------------------------------------------------- handler


class ApiHandler(obs_http._Handler):
    """The `/v1` request router, shared by gateway and replica fronts.

    Extends the pull front's handler (GET /metrics //healthz //readyz
    keep working on the same port) with the solve API. TT605: every
    branch here bounds its socket reads by Content-Length and only
    calls the server's `api` object — whose entire surface enqueues
    commands or reads cached/queue state."""

    def do_GET(self):  # noqa: N802 (http.server's naming)
        path, _, query = self.path.partition("?")
        if path.startswith("/v1/jobs/"):
            params = dict(p.split("=", 1)
                          for p in query.split("&") if "=" in p)
            status, obj = self.server.api.job_view(
                self._job_id(path),
                with_records=params.get("records") != "0",
                with_snapshot=params.get("snapshot") == "1")
            if status is None:
                # an injected `snapshot_ship` die: absorbed as a
                # dropped connection (the `scrape` site's discipline —
                # a SystemExit escaping the handler thread would trip
                # process-wide excepthook machinery)
                self.close_connection = True
                return
            self._reply_json(status, obj)
        elif path == "/v1/jobs":
            # bulk state-only view: the gateway's steady-state poll is
            # ONE of these per replica per tick, not one GET per job
            status, obj = self.server.api.jobs_view()
            self._reply_json(status, obj)
        elif path == "/v1/fleet":
            status, obj = self.server.api.fleet_view()
            self._reply_json(status, obj)
        elif path == "/v1/incident":
            # tt-flight: the newest incident bundle, from the
            # recorder's in-memory `latest()` — replicas serve their
            # own, the gateway its (possibly stitched) one. Read-only
            # and file-I/O-free on this thread (TT602/TT606)
            status, obj = self.server.api.incident_view()
            self._reply_json(status, obj)
        elif path == "/v1/usage":
            # tt-meter (obs/usage.py): per-tenant / per-job capacity
            # attribution — a replica serves its own ledger + live job
            # meters, the gateway the fleet-wide aggregation over its
            # prober-cached per-replica payloads (dead replicas
            # contribute their last-scraped ledger). Read-only on this
            # thread (TT607: handlers read the ledger, never mutate)
            status, obj = self.server.api.usage_view()
            self._reply_json(status, obj)
        else:
            super().do_GET()

    @staticmethod
    def _job_id(path: str) -> str:
        # clients QUOTE the id into the URL (ReplicaHandle, tt
        # submit); without the matching unquote here an id with a
        # space would 404 every poll — which _poll_jobs reads as
        # "replica lost the job" and fails over, forever
        return urllib.parse.unquote(path[len("/v1/jobs/"):])

    def do_POST(self):  # noqa: N802
        path, _, _ = self.path.partition("?")
        if path == "/v1/solve":
            body = self._body()
            if body is None:
                return
            try:
                payload = parse_solve_body(body)
            except ValueError as e:
                self._reply_json(400, {"error": str(e)[:300]})
                return
            status, obj = self.server.api.accept_solve(
                payload, flow=self._flow_header(),
                resubmit=self._resubmit_header())
            self._reply_json(status, obj)
        elif path == "/v1/drain":
            # consume any declared body BEFORE the 200: a keep-alive
            # client's next request must not be parsed out of the
            # leftover payload bytes (the >=400 path closes the
            # connection instead — _reply)
            self._discard_body()
            path_, _, query = self.path.partition("?")
            params = dict(p.split("=", 1)
                          for p in query.split("&") if "=" in p)
            status, obj = self.server.api.accept_drain(
                mode=params.get("mode", "graceful"),
                replica=params.get("replica"))
            self._reply_json(status, obj)
        else:
            self._reply_json(404, {"error": f"no route {path!r}"})

    def do_DELETE(self):  # noqa: N802
        path, _, _ = self.path.partition("?")
        if path.startswith("/v1/jobs/"):
            status, obj = self.server.api.accept_cancel(
                self._job_id(path))
            self._reply_json(status, obj)
        else:
            self._reply_json(404, {"error": f"no route {path!r}"})

    def _flow_header(self) -> int:
        """The gateway's cross-process flow id riding `X-TT-Flow`
        (obs/spans.py XFLOW_BASE range), 0 when absent/garbage — pure
        telemetry, so a bad value is ignored, never a 400."""
        try:
            return int(self.headers.get("X-TT-Flow") or 0)
        except ValueError:
            return 0

    def _resubmit_header(self) -> bool:
        """`X-TT-Resubmit: 1` marks a gateway RESEND (failover
        replay/resume): the receiving replica admits the job without
        re-counting it in its tenant `jobs` ledger — the first
        admission already did (tt-meter, obs/usage.py)."""
        return self.headers.get("X-TT-Resubmit") == "1"

    def _discard_body(self) -> None:
        try:
            n = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            n = 0
        if 0 < n <= MAX_BODY:
            self.rfile.read(n)
        elif n > MAX_BODY:
            self.close_connection = True

    def _reply(self, status: int, body: bytes, ctype: str) -> None:
        # error replies may leave an unread request body in the
        # socket (411/413 before the read, POST routes that never
        # read): close the connection rather than let a keep-alive
        # client parse its next request out of the leftover bytes
        if status >= 400:
            self.close_connection = True
        super()._reply(status, body, ctype)

    def _body(self):
        """Content-Length-bounded body read; replies and returns None
        on anything else. An unbounded `rfile.read()` would park this
        handler thread until the client hangs up — exactly the read
        TT605 bans."""
        n = self.headers.get("Content-Length")
        if n is None:
            self._reply_json(411, {"error": "Content-Length required"})
            return None
        try:
            n = int(n)
        except ValueError:
            self._reply_json(400, {"error": "bad Content-Length"})
            return None
        if n < 0 or n > MAX_BODY:
            self._reply_json(
                413, {"error": f"body over {MAX_BODY} bytes"})
            return None
        return self.rfile.read(n)


# ---------------------------------------------------------------- gateway


class GatewayJob:
    """One job's gateway-side life: payload kept for failover replay,
    state/result/records mirrored from the owning replica by the
    dispatcher's polls (handlers read ONLY this cache)."""

    def __init__(self, job_id: str, payload: dict, now: float):
        self.id = job_id
        self.payload = payload
        self.counts = None           # payload_counts result
        self.bucket = None
        self.replica = None          # owning replica name
        self.state = "accepted"
        self.result = None
        self.error = None
        self.records: list = []
        self.records_final = False
        self.records_truncated = False   # tail lost records (over-cap
        #                                  ring, or a settle fallback)
        #                                  — identity cannot hold
        self.extra_polls = 0         # terminal-tail settle budget
        self.place_attempts = 0
        self.place_started = None    # current placement round's epoch:
        #                              reset by failover, so a job that
        #                              ran for hours still gets the
        #                              full --place-timeout to wait
        #                              out a respawning replica
        self.cancel_requested = False
        self.sent_any = False        # some send of this payload may
        #                              have reached a replica: later
        #                              sends are idempotent resends
        #                              (409 = already placed)
        self.submitted_t = now
        self.finished_t = None
        self.counted = False         # terminal counters bumped once
        self.flow = 0                # cross-process causal flow id
        #                              (obs/spans.py XFLOW_BASE range),
        #                              minted by the dispatcher at first
        #                              placement and shipped to the
        #                              replica as X-TT-Flow — gateway
        #                              and replica spans share it
        self.routed_any = False      # a routed span was emitted: later
        #                              placements (failover) measure
        #                              from THEIR round's start, so the
        #                              job's routed spans never overlap
        #                              and their sum stays a real
        #                              placement-time total
        # -- resume, don't replay (README "Fleet resume") ----------------
        self.prefix: list = []       # records of PREVIOUS incarnations
        #                              (accumulated at each resume):
        #                              the settled stream is
        #                              prefix + the final replica's
        #                              tail — whole and duplicate-free
        self.snap = None             # newest fingerprint-valid wire
        #                              snapshot fetched from the owner
        self.snap_records: list = []  # the record prefix shipped WITH
        #                              that snapshot (one consistent
        #                              park-fence pair)
        self.snap_gens = 0           # progress of the cached snapshot
        #                              (fetch throttle + the
        #                              oldest-progress-first eviction
        #                              key)
        self.snap_bytes = 0          # cache accounting vs
        #                              --snapshot-hwm
        self.snap_truncated = False  # the shipped prefix was capped —
        #                              identity honestly disclaimed
        self.prefix_truncated = False  # some attached prefix was
        #                              capped: the settled stream must
        #                              carry records_truncated
        self.edit_basis = None       # inline instance kept past settle
        #                              (the payload is released there):
        #                              a finished job may still become
        #                              an edit BASE (tt-edit) — bounded
        #                              by --retain-terminal eviction

    def terminal(self) -> bool:
        return self.state in TERMINAL

    def view(self, with_records: bool = True) -> dict:
        out = {"id": self.id, "state": self.state,
               "replica": self.replica,
               "bucket": list(self.bucket) if self.bucket else None,
               "result": self.result, "error": self.error}
        if with_records:
            out["records"] = list(self.records)
            out["records_truncated"] = self.records_truncated
        return out


class GatewayApi:
    """The handlers' surface: enqueue-or-read-only over the Gateway
    (TT605 — no outbound I/O, no device, no registry mutation)."""

    def __init__(self, gw: "Gateway"):
        self._gw = gw

    def accept_solve(self, payload: dict, flow: int = 0,
                     resubmit: bool = False):
        # `flow` (an upstream X-TT-Flow) is accepted for signature
        # parity with ReplicaApi but ignored: the gateway is the ROOT
        # allocator of cross-process chains — its dispatcher mints
        # each job's flow at first placement; likewise `resubmit` —
        # the gateway originates resends, it never receives them
        del flow, resubmit
        gw = self._gw
        if gw.draining:
            return 503, {"error": "draining", "reasons": ["draining"]}
        try:
            counts = payload_counts(payload)
        except ValueError as e:
            return 400, {"error": str(e)[:300]}
        with gw.jobs_lock:
            job_id = payload.get("id")
            if job_id is None:
                # auto-ids skip anything a client already claimed —
                # an id-less submission must never be rejected for a
                # collision it did not cause
                job_id = f"gw-{next(gw.auto_id)}"
                while job_id in gw.jobs:
                    job_id = f"gw-{next(gw.auto_id)}"
            job_id = str(job_id)
            if job_id in gw.jobs:
                return 409, {"error": "duplicate job id", "id": job_id,
                             "state": gw.jobs[job_id].state}
            active = sum(1 for j in gw.jobs.values()
                         if not j.terminal())
            if active >= gw.cfg.backlog:
                return 429, {"error": f"gateway backlog full "
                                      f"({gw.cfg.backlog} active)"}
            job = GatewayJob(job_id, dict(payload, id=job_id),
                             gw.now())
            job.counts = counts
            gw.jobs[job_id] = job
        gw.inbox.put(("submit", job_id))
        return 202, {"id": job_id, "state": "accepted"}

    def job_view(self, job_id: str, with_records: bool = True,
                 with_snapshot: bool = False):
        with self._gw.jobs_lock:
            job = self._gw.jobs.get(job_id)
            if job is None:
                return 404, {"error": f"unknown job {job_id!r}"}
            view = job.view(with_records=with_records)
            if with_snapshot and job.snap is not None:
                # protocol parity with the replica front: the gateway
                # re-serves its cached snapshot, so a client (or a
                # meta-gateway) can pull a warm start for a job even
                # after its replica died
                view["snapshot"] = job.snap
                view["snapshot_records"] = list(job.snap_records)
                view["snapshot_truncated"] = job.snap_truncated
            return 200, view

    def jobs_view(self):
        """Bulk state-only view (protocol parity with the replica
        front — a meta-gateway could poll this gateway the same
        way)."""
        with self._gw.jobs_lock:
            return 200, {"jobs": {j.id: {"state": j.state,
                                         "replica": j.replica}
                                  for j in self._gw.jobs.values()}}

    def accept_cancel(self, job_id: str):
        gw = self._gw
        with gw.jobs_lock:
            job = gw.jobs.get(job_id)
            if job is None:
                return 404, {"error": f"unknown job {job_id!r}"}
            if job.terminal():
                return 409, {"id": job_id, "state": job.state,
                             "error": "already terminal"}
        gw.inbox.put(("cancel", job_id))
        return 202, {"id": job_id, "cancelling": True}

    def accept_drain(self, mode: str = "graceful", replica=None):
        gw = self._gw
        if mode not in ("graceful", "preempt"):
            return 400, {"error": f"unknown drain mode {mode!r} "
                                  f"(graceful | preempt)"}
        if mode == "preempt" and replica is None:
            # a gateway-wide preempt would strand every job (nothing
            # left to resume ON); the supported form names the one
            # replica being scaled down — refuse loudly rather than
            # silently running the graceful full drain instead
            return 400, {"error": "gateway preempt needs a target: "
                                  "?mode=preempt&replica=NAME"}
        if replica is not None:
            # targeted scale-down: POST /v1/drain?mode=preempt&
            # replica=NAME preempts ONE replica — it parks + ships
            # every job it owns, the dispatcher resumes them
            # elsewhere, and the fleet keeps serving (README "Fleet
            # resume"). Only enqueue here (TT605); the dispatcher owns
            # the outbound drain call.
            if mode != "preempt":
                return 400, {"error": "replica= drains require "
                                      "mode=preempt"}
            if gw.replicas.get(replica) is None:
                return 404, {"error": f"unknown replica {replica!r}"}
            gw.inbox.put(("preempt", replica))
            return 202, {"preempting": replica}
        gw.draining = True
        gw.inbox.put(("drain",))
        with gw.jobs_lock:
            active = sum(1 for j in gw.jobs.values()
                         if not j.terminal())
        return 200, {"draining": True, "active": active}

    def incident_view(self):
        """GET /v1/incident at the gateway: its newest bundle — after
        a failover or burn, the STITCHED cross-process one (own rings
        + the involved replicas' pulled bundles). Same shared wire
        shape and in-memory discipline as the replica's
        (obs/flight.incident_response)."""
        from timetabling_ga_tpu.obs.flight import incident_response
        return incident_response(self._gw.flight)

    def usage_view(self):
        """GET /v1/usage at the gateway: fleet-wide totals aggregated
        over the prober's cached per-replica `/v1/usage` payloads
        (ReplicaHandle.last_usage — refreshed on the PROBER thread; a
        DEAD replica keeps contributing its last-scraped ledger, the
        incident-bundle stitching rule, so a killed replica's metered
        work never vanishes from the bill). Tenant meters SUM — each
        replica counted only its own metered quanta, and a resumed
        job's survivor ledger starts from zero — so a failover's
        fleet totals match an uninterrupted solve's modulo the re-run
        quantum (tests/test_usage.py pins it). Read-only over handle
        attributes on this handler thread (TT605/TT607)."""
        gw = self._gw
        payloads = [(h.name, h.dead, h.usage_payload())
                    for h in gw.replicas.all()]
        from timetabling_ga_tpu.obs import usage as obs_usage
        return 200, obs_usage.aggregate(payloads)

    def fleet_view(self):
        # served from the dispatcher's lock-guarded SNAPSHOT, refreshed
        # once per tick — the handler thread never reads router/replica
        # state the dispatcher is mutating (the live view used to walk
        # `router._pins` mid-placement). The JSON is a convenience: the
        # same numbers are real /metrics families (fleet.replica.*,
        # fleet.route.*, fleet.jobs_* — module docstring maps them)
        return 200, self._gw.fleet_snapshot()


class Gateway:
    """The fleet front: HTTP API + single-threaded dispatcher that
    owns routing, submission, polling, failover, and drain."""

    def __init__(self, cfg: FleetConfig, handles, owned: bool = False,
                 now=None, out=None, spawn_fn=None):
        # deterministic fault injection, mirroring SolveService: the
        # gateway/route sites fire under `tt fleet` too
        spec = faults.active_spec(cfg.faults)
        if spec:
            faults.install(spec)
        self.cfg = cfg
        self.now = now or time.monotonic
        self.owned = owned           # gateway manages replica lifetime
        self.draining = False
        self.drained = threading.Event()
        self.jobs: dict = {}
        self.jobs_lock = threading.RLock()
        self.auto_id = itertools.count(1)
        self.inbox = queue_mod.Queue()
        self._requeue: list = []     # placement retries, drained ONCE
        #                              per poll tick (an inbox requeue
        #                              would be popped right back and
        #                              starve the poll/drain phases)
        self._terminal_order: list = []   # settled ids, eviction FIFO
        # the gateway's PRIVATE registry (replicas keep their own
        # /readyz truths; so does the front) — created before the
        # telemetry stream so the tt-flight pieces can report into it
        self.registry = obs_metrics.MetricsRegistry()
        # tt-flight: the history ring samples this registry (whose
        # per-replica pull gauges the prober refreshes — so
        # `sustained("fleet.replica.r0.backlog", ...)` is exactly the
        # autoscaling loop's input, ROADMAP item 3); the recorder tees
        # the gateway log and stitches cross-process bundles on
        # failover/burn (`_pull_incidents` is its peer fetch, run on
        # the RECORDER thread — a hung replica export parks the
        # recorder, never the dispatcher)
        self.history = None
        self.flight = None
        self._stream = None
        self._close_stream = False
        self.writer = None
        self.front = None
        self.replicas = None
        self.scaler = None
        try:
            self._init_rest(cfg, handles, out, spawn_fn)
        except BaseException:
            # ANY constructor failure past the thread starts — a taken
            # listen port, an unwritable -o path, a bad worker-flag
            # parse — must not leak the started tt-flight threads, the
            # gw_writer worker, the -o handle, the prober thread, or
            # owned worker processes into a process whose Gateway
            # never existed (the SolveService ctor-failure discipline;
            # close() is unreachable here)
            if self.front is not None:
                self.front.close()
            if self.scaler is not None:
                self.scaler.close()
            if self.flight is not None:
                self.flight.close()
            if self.history is not None:
                self.history.close()
            if self.writer is not None:
                try:
                    self.writer.close(raise_error=False)
                except Exception:
                    pass
            if self._close_stream:
                try:
                    self._stream.close()
                except Exception:
                    pass
            if self.replicas is not None:
                self.replicas.close()
            raise

    def _init_rest(self, cfg: FleetConfig, handles, out,
                   spawn_fn=None) -> None:
        # -- telemetry stream (tt-obs v5): `-o LOG` (or an explicit
        # `out` stream) gives the gateway its own AsyncWriter + tracer;
        # without one the tracer is the shared no-op and nothing emits
        self._stream = out
        if self._stream is None and cfg.output:
            self._stream = open(cfg.output, "w")
            self._close_stream = True
        from timetabling_ga_tpu.obs import flight as obs_flight
        self.history, self.flight, sink = obs_flight.wire(
            cfg, self._stream, registry=self.registry,
            process="gateway", peers_fn=self._pull_incidents,
            now=self.now, history_always=True)
        self.writer = (jsonl.AsyncWriter(sink, site="gw_writer")
                       if sink is not None else None)
        self._obs_dead = False       # latched by _rec on a dead writer
        self.tracer = (SpanTracer(self.writer, clock=self.now,
                                  flow_base=XFLOW_BASE)
                       if self.writer is not None else NULL_TRACER)
        if self.flight is not None:
            if self.writer is not None:
                self.flight.bind_tracer(self.tracer)
            self.flight.start()
        # the serve flags spawned workers run with double as the
        # router's bucket spec — one parse, no drift
        serve_cfg = (parse_serve_args(cfg.serve_args)
                     if cfg.serve_args else ServeConfig())
        # kept whole: the snapshot cache validates shipped snapshots
        # against the fleet's (bucket, pop_size, seed) fingerprint —
        # the same parse the workers run with, so it cannot drift
        self.serve_cfg = serve_cfg
        self.spec = BucketSpec(
            event_floor=serve_cfg.bucket_events,
            room_floor=serve_cfg.bucket_rooms,
            feature_floor=serve_cfg.bucket_features,
            student_floor=serve_cfg.bucket_students,
            ratio=serve_cfg.bucket_ratio)
        from timetabling_ga_tpu.fleet.replicas import ReplicaSet
        self.replicas = ReplicaSet(
            handles, probe_every=cfg.probe_every,
            probe_timeout=cfg.probe_timeout,
            dead_after=cfg.dead_after, max_restarts=cfg.max_restarts,
            on_death=self._on_death, boot_grace=cfg.boot_grace)
        self.router = Router(self.replicas, registry=self.registry)
        self.registry.gauge_fn(
            "fleet.replicas_ready",
            lambda: sum(1 for h in self.replicas.live() if h.ready))
        self.registry.gauge_fn(
            "serve.queue_depth",
            lambda: sum(1 for j in list(self.jobs.values())
                        if not j.terminal()))
        self.registry.gauge("serve.backlog").set(cfg.backlog)
        for h in handles:
            self._bind_replica_gauges(h)
        if self.writer is not None:
            self.registry.gauge_fn("writer.queue_depth",
                                   self.writer.qsize)
        # dispatcher watchdog: tick age as a pull gauge + the
        # configured threshold, so /readyz (obs/http.py readiness) can
        # flip `dispatcher_stalled` from registry state alone
        self._ticks = 0
        self._last_tick = self.now()
        self.registry.gauge_fn("fleet.tick_age_s",
                               lambda: self.now() - self._last_tick)
        self.registry.gauge("fleet.tick_stall_after").set(
            cfg.stall_after)
        # snapshot cache accounting (README "Fleet resume"): live
        # gauges so the resume story is on /metrics before any
        # failover ever needs it
        if cfg.snapshot_hwm > 0:
            self.registry.gauge("fleet.resume.bytes").set(0.0)
            self.registry.gauge("fleet.resume.cached").set(0.0)
        # SLO monitor (--slo-p99): rolling window of e2e latencies,
        # p99'd once per tick; transitions emit faultEntry records
        self._slo_lat = collections.deque(maxlen=cfg.slo_window)
        self._slo_burning = False
        if cfg.slo_p99 > 0:
            self.registry.gauge("fleet.slo_burn").set(0.0)
        # /v1/fleet snapshot: refreshed by the dispatcher each tick,
        # served by handlers under _view_lock (never the live state)
        self._view_lock = threading.Lock()
        self._view_cache: dict = {}
        # tt-scale inputs published alongside it: per-replica in-flight
        # counts and the warmth-guard protections, computed ON the
        # dispatcher (the only thread that may read router warmth) and
        # read by the SCALER thread under the same lock
        self._scale_cache: dict = {}
        self._bucket_routed_t: dict = {}   # bucket -> last placement
        #                                    time (the warmth guard's
        #                                    'recently routed' input)
        self._thread = threading.Thread(
            target=self._dispatch_loop, name="tt-fleet-dispatch",
            daemon=True)
        # tt-scale (fleet/autoscaler.py, README "Autoscaling"): the
        # policy actuator, constructed before the front so /healthz
        # can probe it, started by start(). Scale-up needs the --spawn
        # worker pool; an injected spawn_fn is the test seam (and how
        # a dry-run over a static fleet stays actuation-free).
        probes = {"dispatcher": self._thread.is_alive}
        if cfg.scale_max > 0:
            from timetabling_ga_tpu.fleet.autoscaler import AutoScaler
            if spawn_fn is None and self.owned \
                    and not cfg.scale_dry_run:
                from timetabling_ga_tpu.fleet import (
                    replicas as replicas_mod)

                def spawn_fn(name, cfg=cfg):
                    return replicas_mod.spawn_one(cfg, name)

            self.scaler = AutoScaler(self, cfg, spawn_fn=spawn_fn,
                                     now=self.now)
            probes["scaler"] = self.scaler.alive
        # a taken listen port raises here — __init__'s outer guard
        # closes every thread/handle started above
        self.front = obs_http.ObsServer(
            cfg.listen, registry=self.registry,
            probes=probes,
            handler=ApiHandler, api=GatewayApi(self),
            site="gateway", history=self.history)
        self._refresh_view()

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "Gateway":
        # one synchronous probe round before anything routes: the
        # router's first decision should see real readiness, not the
        # all-unprobed default
        self.replicas.probe_all()
        self.replicas.start()
        self.front.start()
        self._thread.start()
        if self.scaler is not None:
            self.scaler.start()
        return self

    @property
    def url(self) -> str:
        return self.front.url

    def request_drain(self) -> None:
        self.draining = True
        self.inbox.put(("drain",))

    def adopt_replica(self, handle) -> None:
        """tt-scale scale-up (runs on the SCALER thread — the only
        legal actuation site, TT608): register a just-spawned worker.
        The prober picks it up next round (`--boot-grace` covers its
        jax import, exactly like a startup spawn), the router sees it
        once ready, and its gauges join the fleet.replica.* families
        the history ring samples. Handle-set and registry mutations
        only — router state stays the dispatcher's."""
        self.replicas.add(handle)
        self._bind_replica_gauges(handle)

    def preempt_replica(self, name: str) -> None:
        """Targeted lossless scale-down (README "Fleet resume"):
        preempt ONE replica — it parks + ships every job it owns, the
        dispatcher resumes them on the surviving fleet. Same path as
        POST /v1/drain?mode=preempt&replica=NAME."""
        self.inbox.put(("preempt", name))

    def close(self) -> None:
        # the scaler goes first: it emits records through the writer
        # being drained below and actuates through the dispatcher
        # being stopped below
        if self.scaler is not None:
            self.scaler.close()
        self._stop = True
        self.inbox.put(("wake",))
        if self._thread.ident is not None:   # never-started (close
            self._thread.join(timeout=5.0)   # before start): no join
        if self.writer is not None:
            # final registry snapshot, then drain the telemetry log —
            # raise_error=False: a latched writer error must not mask
            # the caller's own teardown
            self._rec(jsonl.metrics_entry, self.writer,
                      self.registry.snapshot(), ts=self.tracer.now())
            try:
                self.writer.close(raise_error=False)
            except Exception:
                pass
        # flight teardown AFTER the writer drains (the engine/serve
        # ordering): a last-tick failover's faultEntry and spans must
        # reach the tee's rings before the recorder's final poll dumps
        # the pending trigger's bundle
        if self.flight is not None:
            self.flight.close()
        if self.history is not None:
            self.history.close()
        if self.writer is not None and self._close_stream:
            try:
                self._stream.close()
            except Exception:
                pass
        self.front.close()
        self.replicas.close()

    # -- telemetry plumbing (tt-obs v5) ---------------------------------

    def _rec(self, fn, *args, **kw) -> None:
        """Guarded record emission (routeEntry / metricsEntry /
        faultEntry / tracer.record): the `gw_writer` isolation
        contract — a dead gateway log writer latches obs OFF and the
        dispatcher routes on; it never stalls placement or
        settlement."""
        if self.writer is None or self._obs_dead:
            return
        try:
            fn(*args, **kw)
        except Exception:
            self._obs_dead = True
            self.tracer.enabled = False

    def _bind_replica_gauges(self, h) -> None:
        """Per-replica /metrics families (ROADMAP item 3's gateway
        parity): the same numbers `/v1/fleet` shows, as pull gauges
        over the handle's probe state. A None field (never probed)
        reads as NaN — Gauge.value degrades, never raises."""
        base = f"fleet.replica.{h.name}"
        reg = self.registry
        reg.gauge_fn(f"{base}.ready",
                     lambda h=h: 0.0 if h.dead else float(h.ready))
        reg.gauge_fn(f"{base}.backlog",
                     lambda h=h: float(h.queue_depth))
        reg.gauge_fn(f"{base}.probe_seconds",
                     lambda h=h: float(h.probe_seconds))
        reg.gauge_fn(f"{base}.compile_hit_rate",
                     lambda h=h: float(h.compile_hit_rate()))
        reg.gauge_fn(f"{base}.pins",
                     lambda h=h: float(
                         self.router.pin_counts.get(h.name, 0)))
        reg.gauge_fn(f"{base}.restarts",
                     lambda h=h: float(h.restarts))

    def _pull_incidents(self, names) -> list:
        """The flight recorder's peer fetch (RECORDER thread, never the
        dispatcher — a hung replica export parks the recorder, routing
        and settlement run on): each involved replica's newest
        GET /v1/incident bundle, falling back to the prober's last
        cached copy (ReplicaHandle.last_incident) when the replica is
        already dead — the usual case at failover, and exactly the
        "30 seconds before" evidence the cache exists for."""
        out = []
        for name in names:
            handle = self.replicas.get(name)
            if handle is None:
                out.append((name, None, "unknown replica"))
                continue
            bundle, err = None, None
            if not handle.dead:
                try:
                    bundle = handle.get_incident(
                        timeout=self.cfg.snapshot_timeout)
                except Exception as e:
                    err = str(e)[:120]
            if bundle is None and handle.last_incident is not None:
                bundle = handle.last_incident
                err = None if err is None else err + " (cached copy)"
            if bundle is None and err is None:
                err = ("dead, no cached bundle" if handle.dead
                       else "no incident recorded")
            out.append((name, bundle, err))
        return out

    def _refresh_view(self) -> None:
        """Rebuild the /v1/fleet snapshot ON the dispatcher (the only
        thread mutating router/job state) and publish it under the
        view lock — fleet_view handlers read the copy, racing
        nothing. The tt-scale snapshot is computed here too: the
        warmth guard reads router warmth and the job table, both
        owned by this thread, so the SCALER thread only ever sees a
        published copy."""
        with self.jobs_lock:
            states: dict = {}
            inflight_by_rep: dict = {}
            hot: set = set()
            for j in self.jobs.values():
                states[j.state] = states.get(j.state, 0) + 1
                if not j.terminal():
                    if j.replica is not None:
                        inflight_by_rep[j.replica] = (
                            inflight_by_rep.get(j.replica, 0) + 1)
                    if j.bucket is not None:
                        hot.add(j.bucket)
        # the tt-scale snapshot is only ever read by the scaler
        # thread — with the autoscaler off this dispatcher tick does
        # none of the warmth/load bookkeeping
        scale = None
        if self.scaler is not None:
            # hot buckets: in-flight jobs' buckets plus anything
            # routed within --scale-warm-recent (entries beyond the
            # window are pruned — the dict stays bounded by live
            # bucket churn)
            now = self.now()
            for bucket, t in list(self._bucket_routed_t.items()):
                if now - t <= self.cfg.scale_warm_recent:
                    hot.add(bucket)
                else:
                    del self._bucket_routed_t[bucket]
            # warmth protection considers SURVIVING capacity only: a
            # retiring replica is still draining (and warm), but it
            # is leaving — counting it as a warm owner would leave a
            # hot bucket's last remaining home unprotected
            live = [h for h in self.replicas.live()
                    if not getattr(h, "retired", False)]
            protected: dict = {}
            for bucket in hot:
                owner = self.router.sole_warm_owner(
                    bucket, [h.name for h in live])
                if owner is not None:
                    protected.setdefault(owner, []).append(
                        list(bucket))
            scale = {
                "replicas": {
                    h.name: {"dead": h.dead,
                             "retired": getattr(h, "retired", False),
                             "inflight": inflight_by_rep.get(h.name,
                                                             0),
                             "pins": self.router.pin_counts.get(
                                 h.name, 0),
                             # serve.resident_* gauges off the last
                             # probe: the residency-aware victim
                             # preference (autoscaler choose_victim)
                             "resident_groups": getattr(
                                 h, "resident_groups", None),
                             "resident_bytes": getattr(
                                 h, "resident_bytes", None)}
                    for h in self.replicas.all()},
                "protected": protected}
        view = {"replicas": [h.view() for h in self.replicas.all()],
                "router": self.router.stats(),
                "jobs": states, "draining": self.draining}
        with self._view_lock:
            self._view_cache = view
            if scale is not None:
                self._scale_cache = scale

    def fleet_snapshot(self) -> dict:
        with self._view_lock:
            return self._view_cache

    def scale_snapshot(self) -> dict:
        """The autoscaler's warmth/load inputs, as last published by
        the dispatcher tick (read on the SCALER thread)."""
        with self._view_lock:
            return self._scale_cache

    def _slo_tick(self) -> None:
        """--slo-p99 rolling-window monitor: p99 over the last
        `--slo-window` settled jobs' e2e latencies, once per tick. A
        burn start/clear flips the `fleet.slo_burn` gauge (the /readyz
        `slo_burn` reason) and emits a faultEntry on the gateway log —
        the moment the fleet stops meeting its latency objective is an
        EVENT, not just a dashboard drift."""
        if self.cfg.slo_p99 <= 0 or not self._slo_lat:
            return
        lats = sorted(self._slo_lat)
        p99 = lats[min(len(lats) - 1, int(0.99 * len(lats)))]
        self.registry.gauge("fleet.slo_p99_s").set(p99)
        burning = p99 > self.cfg.slo_p99
        if burning == self._slo_burning:
            return
        self._slo_burning = burning
        self.registry.gauge("fleet.slo_burn").set(
            1.0 if burning else 0.0)
        if burning:
            self.registry.counter("fleet.slo_burns").inc()
            if self.flight is not None:
                # a burn START is an incident: stitch the whole live
                # fleet's bundles — every replica is "involved" in a
                # latency objective (the pull runs on the recorder
                # thread; this call only enqueues)
                self.flight.trigger(
                    "slo_burn",
                    peers=[h.name for h in self.replicas.live()])
        self._rec(jsonl.fault_entry, self.writer, "slo_burn",
                  "burn" if burning else "clear",
                  f"rolling p99 {p99:.3f}s vs SLO "
                  f"{self.cfg.slo_p99:.3f}s", 0, 0, 0,
                  self.tracer.now(), window=len(lats),
                  p99_s=round(p99, 6))

    def _tick_done(self, t0: float) -> None:
        """End-of-tick bookkeeping: loop timing, the watchdog's tick
        stamp, the SLO check, the /v1/fleet snapshot refresh, and the
        periodic metricsEntry."""
        now = self.now()
        self.registry.histogram("fleet.tick_seconds").observe(
            now - t0)
        self._last_tick = now
        self._ticks += 1
        self._slo_tick()
        self._refresh_view()
        if (self.writer is not None and self.cfg.metrics_every > 0
                and self._ticks % self.cfg.metrics_every == 0):
            self._rec(jsonl.metrics_entry, self.writer,
                      self.registry.snapshot(), ts=self.tracer.now())

    # -- the dispatcher thread: ALL outbound I/O lives here -------------

    _stop = False

    def _dispatch_loop(self) -> None:
        try:
            while not self._stop:
                try:
                    cmd = self.inbox.get(timeout=self.cfg.poll_every)
                except queue_mod.Empty:
                    cmd = None
                t0 = self.now()   # tick timing excludes the idle wait
                while cmd is not None:
                    self._handle(cmd)
                    try:
                        cmd = self.inbox.get_nowait()
                    except queue_mod.Empty:
                        cmd = None
                self._poll_jobs()
                # deferred placement retries AFTER the poll phase, one
                # round per tick: a replica paying its boot-time jax
                # import must not starve status polls or drain progress
                retries, self._requeue = self._requeue, []
                for job_id in retries:
                    self._handle(("submit", job_id))
                self._drain_tick()
                self._tick_done(t0)
        except SystemExit:
            # injected `route`/`gateway` die: ends THIS thread only —
            # /healthz's dispatcher probe goes false, replicas run on
            return

    def _handle(self, cmd: tuple) -> None:
        kind = cmd[0]
        if kind == "submit":
            with self.jobs_lock:
                job = self.jobs.get(cmd[1])
            if job is not None and not job.terminal():
                if job.cancel_requested:
                    # cancelled while waiting for placement: settle
                    # locally, nothing to route
                    job.state = "cancelled"
                    self._settle(job)
                    return
                if job.place_attempts == 0:   # not a requeue retry
                    self.registry.counter("fleet.jobs_accepted").inc()
                if not job.flow:
                    # the job's CROSS-PROCESS flow id, minted once on
                    # the dispatcher (handlers only enqueue): every
                    # gateway span of this job and — via the
                    # X-TT-Flow header — every replica-side span
                    # shares it
                    job.flow = self.tracer.new_flow()
                if job.place_started is None:
                    job.place_started = self.now()
                edit = (job.payload or {}).get("edit")
                if (isinstance(edit, dict)
                        and isinstance(edit.get("base"), str)
                        and not self._resolve_edit(job)):
                    return        # _resolve_edit already failed it
                self._place(job)
        elif kind == "cancel":
            self._cancel(cmd[1])
        elif kind == "drain":
            self.registry.gauge("serve.draining").set(1.0)
        elif kind == "failover":
            self._failover(cmd[1])
        elif kind == "preempt":
            # targeted scale-down: tell ONE replica to park + ship.
            # The poll loop then sees its jobs turn `preempted`,
            # refreshes their snapshots, and resumes them elsewhere —
            # lossless scale-down (README "Fleet resume")
            handle = self.replicas.get(cmd[1])
            if handle is not None and not handle.dead:
                try:
                    handle.drain(timeout=self.cfg.probe_timeout,
                                 mode="preempt")
                except Exception:
                    pass       # prober/failover own an unreachable one
        # "wake" and anything else: just a loop tick

    def _resolve_edit(self, job: GatewayJob) -> bool:
        """Resolve an edit payload's job-id base on the dispatcher
        (tt-edit; README "Incremental re-solve"): rewrite
        `edit["base"]` from the base job's own payload (the inline
        instance every replica can parse), remember the id in
        `edit["base_id"]`, and attach the freshest base snapshot —
        the client's own, the `--snapshot-hwm` cache's, or a live
        `?snapshot=1` fetch from the base's owner. The rewritten
        payload is CONCRETE: a failover replays it byte-stable with
        no second resolution (the base job may be long gone by then).
        False = the job was failed here (unknown/unusable base)."""
        edit = dict((job.payload or {}).get("edit") or {})
        base_id = edit.get("base")
        if not isinstance(base_id, str):
            return True
        with self.jobs_lock:
            base_job = self.jobs.get(base_id)
        if base_job is None:
            self._fail(job, f"edit base job {base_id!r} unknown to "
                            f"this gateway")
            return False
        bp = base_job.payload or {}
        inline = {k: bp[k] for k in ("tim", "problem", "n_days",
                                     "slots_per_day") if k in bp}
        if "tim" not in inline and "problem" not in inline:
            # the base is itself an edit job: its payload holds an
            # edit spec, not an instance — usable only when that spec
            # shipped the full edited instance (an ops-built base
            # would need the gateway to apply ops, which is the
            # replica's job by layering). A SETTLED base's payload is
            # released wholesale — its instance lives on in
            # edit_basis until --retain-terminal evicts the job
            base_edit = bp.get("edit") or {}
            edited = base_edit.get("edited")
            if isinstance(edited, dict):
                inline = dict(edited)
            elif base_job.edit_basis:
                inline = dict(base_job.edit_basis)
            else:
                self._fail(
                    job, f"edit base job {base_id!r} carries no "
                         f"inline instance (an edit of an ops-built "
                         f"edit job is not resolvable at the "
                         f"gateway; submit the base with 'edited')")
                return False
        wire = edit.get("snapshot")
        if wire is None:
            wire = base_job.snap
            if wire is None and base_job.replica:
                # live grab from the base's owner (dispatcher thread,
                # snapshot-timeout budget — same as any cache refresh);
                # no snapshot anywhere just means the replica demotes
                # the edit to a cold solve, counted there
                handle = self.replicas.get(base_job.replica)
                if handle is not None and not handle.dead:
                    self._fetch_snapshot(base_job, handle)
                    wire = base_job.snap
        edit["base"] = inline
        edit["base_id"] = base_id
        if wire is not None:
            edit["snapshot"] = wire
        with self.jobs_lock:
            job.payload = dict(job.payload, edit=edit)
        try:
            job.counts = payload_counts(job.payload)
        except ValueError as e:
            self._fail(job, str(e)[:300])
            return False
        if job.counts is None:
            self._fail(job, f"edit base job {base_id!r} resolution "
                            f"yielded no routing counts")
            return False
        return True

    def _place(self, job: GatewayJob, exclude: tuple = ()) -> None:
        """Route + submit one job, failing over across replicas until
        placed or nothing remains."""
        try:
            job.bucket = bucket_key_from_counts(*job.counts,
                                                spec=self.spec)
            with self.tracer.span("route", cat="fleet", job=job.id,
                                  flow=job.flow):
                handle = self.router.route(job.bucket,
                                           exclude=exclude)
        except NoReplicaError as e:
            self._fail(job, str(e))
            return
        except faults.FaultInjected as e:
            self._fail(job, f"routing fault: {e}")
            return
        job.place_attempts += 1
        # one routeEntry per placement decision: the affinity outcome
        # and the exact score inputs the router read (last_decision is
        # same-thread fresh — no other thread routes)
        decision = self.router.last_decision
        self._rec(jsonl.route_entry, self.writer, job.id, job.bucket,
                  handle.name, decision.get("outcome", "?"),
                  backlog=decision.get("backlog"),
                  pins=decision.get("pins"),
                  compile_hit_rate=decision.get("compile_hit_rate"),
                  attempt=job.place_attempts, flow=job.flow)

        def send():
            # DATA-plane timeout: the payload can be a multi-MB
            # problem JSON; the 2 s probe budget is for gauges.
            # Any attempt after the first is an idempotent RESEND
            # (the earlier one may have landed and lost its reply) —
            # only then is a replica's 409 'already have it' success.
            if job.sent_any:
                self.registry.counter("fleet.submit_retries").inc()
            idem = job.sent_any
            job.sent_any = True
            # resubmit (the tt-meter no-rebill header) is keyed on a
            # previously SUCCESSFUL placement (routed_any), not on
            # sent_any: a boot-window retry whose first POST never
            # landed is still the job's first admission and must be
            # billed; a genuine failover resend was already counted
            # by its first replica. (The lost-response resend inside
            # one placement needs no header: the replica answers 409
            # duplicate — no second admission, no second count.)
            return handle.post_job(job.payload,
                                   timeout=self.cfg.io_timeout,
                                   idempotent=idem, flow=job.flow,
                                   resubmit=job.routed_any)

        try:
            with self.tracer.span("submit", cat="fleet", job=job.id,
                                  flow=job.flow, replica=handle.name):
                retry_transient(send,
                                attempts=self.cfg.route_retries,
                                wait_s=self.cfg.retry_wait_s,
                                backoff=2.0, max_wait_s=2.0)
        except Exception as e:
            from timetabling_ga_tpu.runtime.retry import is_transient
            started = (job.place_started if job.place_started
                       is not None else self.now())
            if (is_transient(e) and self.now() - started
                    < self.cfg.place_timeout):
                # a replica still booting or mid-restart: requeue —
                # retried once per poll tick (the deferred list, not
                # the inbox) rather than burning the exclusion list on
                # a process that is paying its jax import (a spawned
                # worker takes many seconds before it binds its port).
                # The window is anchored at THIS placement round, so
                # failover after a long run gets the full budget.
                self._requeue.append(job.id)
                return
            remaining = [h for h in self.replicas.live()
                         if h.name not in exclude
                         and h.name != handle.name]
            if remaining:
                self._place(job, exclude + (handle.name,))
            else:
                self._fail(job, f"no replica accepted job: "
                                f"{str(e)[:200]}")
            return
        job.replica = handle.name
        job.state = "routed"
        # the warmth guard's 'recently routed' input (tt-scale): a
        # bucket placed within --scale-warm-recent is HOT — its sole
        # warm replica must survive scale-down (scaler-off gateways
        # skip the bookkeeping; _refresh_view never prunes it there)
        if self.scaler is not None:
            self._bucket_routed_t[job.bucket] = self.now()
        self.registry.counter("fleet.jobs_routed").inc()
        # the `routed` span: admit-at-gateway → accepted-by-replica
        # for the FIRST placement, failover-instant → re-accepted for
        # every later one (place_started, reset by _reassign) — so a
        # failed-over job's routed spans never overlap and
        # tally("routed") in the tt stats breakdown stays a true
        # placement-time total. Measured on the gateway's own clock
        # (submitted_t/place_started are the tracer's clock domain).
        start = (job.place_started if job.routed_any
                 and job.place_started is not None
                 else job.submitted_t)
        job.routed_any = True
        self._rec(self.tracer.record, "routed", start,
                  max(0.0, self.now() - start), cat="fleet",
                  job=job.id, flow=job.flow, replica=handle.name,
                  attempt=job.place_attempts)

    def _cancel(self, job_id: str) -> None:
        with self.jobs_lock:
            job = self.jobs.get(job_id)
        if job is None or job.terminal():
            return
        # remembered across failover: a job cancelled while its
        # replica is dying must NOT be resubmitted and solved to
        # completion — _failover and the requeue path check this flag
        job.cancel_requested = True
        if job.replica is None:
            job.state = "cancelled"
            self._settle(job)
            return
        handle = self.replicas.get(job.replica)
        if handle is not None:
            try:
                handle.cancel_job(job.id,
                                  timeout=self.cfg.probe_timeout)
            except Exception:
                pass           # polls (or failover) settle the state

    def _poll_jobs(self) -> None:
        """Refresh the cached job table from the owning replicas —
        the ONLY place replica job state enters the gateway. The
        steady-state poll is STATE-ONLY (`?records=0` — a long job's
        tail would otherwise be re-serialized on every tick); the
        record tail is fetched once the job turns terminal, and the
        job settles when that tail carries the terminal jobEntry (the
        replica's AsyncWriter drains asynchronously, so state can
        lead the records by a beat). An over-cap ring tail or an
        exhausted settle budget settles with `records_truncated`
        marked — visible, never a silently frozen partial stream."""
        with self.jobs_lock:
            jobs = [j for j in self.jobs.values()
                    if j.replica is not None
                    and not (j.terminal() and j.records_final)]
        by_replica: dict = {}
        for job in jobs:
            by_replica.setdefault(job.replica, []).append(job)
        if not by_replica:
            return
        # the poll span uses the record() form and is emitted ONLY
        # when the round observed a state change or settlement — a
        # steady-state gateway polling an idle fleet must not fill its
        # log with empty poll brackets at 5 Hz
        t0 = self.now()
        changed = self._poll_replicas(by_replica)
        if changed:
            self._rec(self.tracer.record, "poll", t0,
                      self.now() - t0, cat="fleet",
                      replicas=len(by_replica), jobs=len(jobs),
                      updates=changed)

    def _poll_replicas(self, by_replica: dict) -> int:
        changed = 0
        for name, group in by_replica.items():
            handle = self.replicas.get(name)
            if handle is None or handle.dead:
                continue           # prober + failover own this case
            try:
                states = handle.list_jobs(
                    timeout=self.cfg.probe_timeout)
            except Exception:
                continue           # prober decides life and death
            for job in group:
                info = states.get(job.id)
                if info is None:
                    # a LIVE replica that does not know the job: it
                    # restarted inside the dead_after window and lost
                    # its state — per-job failover, because the
                    # prober sees a healthy process and will never
                    # declare it dead
                    self._reassign(job)
                    changed += 1
                    continue
                state = info.get("state")
                if state == "preempted":
                    # the replica parked + published this job and is
                    # counting down its --preempt-grace: grab the
                    # final snapshot NOW (best effort — a stale cached
                    # one still resumes, just further back) and
                    # re-place the job on the surviving fleet
                    self._fetch_snapshot(job, handle, final=True)
                    self._reassign(job)
                    changed += 1
                    continue
                if not state or state not in TERMINAL:
                    if state and state != job.state:
                        job.state = state
                        changed += 1
                    gens = info.get("gens")
                    if (self.cfg.snapshot_hwm > 0 and gens is not None
                            and int(gens) > job.snap_gens):
                        # progress since the cached snapshot: refresh
                        # the cache from the owner's latest park fence
                        if self._fetch_snapshot(job, handle):
                            changed += 1
                    continue
                # the replica reports terminal — but the gateway view
                # must not SAY so until the record tail is cached, or
                # a fast client reads `done` with an empty stream;
                # state and records publish together at settle
                try:
                    full = handle.get_job(
                        job.id, timeout=self.cfg.io_timeout)
                except Exception:
                    continue
                job.result = full.get("result", job.result)
                job.error = full.get("error", job.error)
                records = full.get("records") or []
                complete = any(
                    rec.get("jobEntry", {}).get("event") in TERMINAL
                    for rec in records)
                truncated = bool(full.get("records_truncated"))
                job.extra_polls += 1
                if complete or truncated or job.extra_polls >= 50:
                    # a resumed job's stream = the accumulated prefix
                    # (records of every previous incarnation through
                    # its shipped fence) + this final incarnation's
                    # tail — whole, duplicate-free (the restored
                    # `emitted` floor), and identical to an
                    # uninterrupted solve modulo timing/fault records.
                    # EXCEPT when the replica REJECTED the attached
                    # snapshot and demoted to a fresh replay (version
                    # skew, foreign fingerprint on a static fleet, an
                    # injected `resume` fault): its tail is then a
                    # complete from-gen-0 stream — detectable by the
                    # `admitted` jobEntry a resumed continuation never
                    # re-emits — and prepending the prefix would
                    # duplicate it wholesale
                    prefix = list(job.prefix)
                    prefix_trunc = job.prefix_truncated
                    if prefix and any(
                            rec.get("jobEntry", {}).get("event")
                            == "admitted" for rec in records):
                        prefix = []
                        prefix_trunc = False
                        self.registry.counter(
                            "fleet.resume.demoted").inc()
                    job.records = prefix + records
                    job.state = state
                    job.records_truncated = (truncated or not complete
                                             or prefix_trunc)
                    self._settle(job)
                    changed += 1
        return changed

    # -- the snapshot cache: resume, don't replay -----------------------

    def _fetch_snapshot(self, job: GatewayJob, handle,
                        final: bool = False) -> bool:
        """Refresh one in-flight job's cached ship unit from its
        owner (`?snapshot=1` — dispatcher thread, data-plane timeout).
        Only a FINGERPRINT-VALID snapshot (bucket + pop size + seed,
        verified stdlib-only via serve/snapshot.verify_wire) enters
        the cache; anything else counts `fleet.resume.rejected` and
        the job keeps its previous snapshot (or falls back to replay
        at failover). `final` marks the preempt-drain grab — fetch
        errors there are expected when the grace deadline races us."""
        if self.cfg.snapshot_hwm <= 0:
            return False
        try:
            # --snapshot-timeout, NOT --io-timeout: this runs on the
            # one dispatcher thread and is an optimization — a hung
            # replica export must cost seconds, not a 30 s io budget
            # times its in-flight jobs (which would starve routing/
            # polling/failover and trip the dispatcher_stalled
            # watchdog); a failed fetch keeps the previous cache
            view = handle.get_job(
                job.id, timeout=self.cfg.snapshot_timeout,
                with_records=False, snapshot=True)
        except Exception:
            self.registry.counter("fleet.resume.fetch_errors").inc()
            return False
        wire = view.get("snapshot")
        if not wire:
            return False
        self.registry.counter("fleet.resume.fetches").inc()
        try:
            # full fingerprint pre-validation only when the gateway
            # OWNS the worker flags (`--spawn N -- ...` — then its
            # parsed serve config IS the workers', no drift possible);
            # a static `--replica URL` fleet's serve config is not the
            # gateway's to know, so the check there is structural
            # (version/CRC/byte-count) + bucket consistency, and the
            # REPLICA's resume admission stays the authoritative
            # fingerprint gate either way (a bad snapshot demotes to
            # replay on arrival, never corrupts a stream)
            expect = None
            if self.cfg.serve_args and job.payload is not None:
                # a SETTLED job's payload (and with it the submit
                # seed) is released — its edit-base grab drops to the
                # structural + bucket check below, and the replica's
                # transplant classification stays the real gate
                seed = int(job.payload.get(
                    "seed", self.serve_cfg.seed))
                expect = snapshot_mod.wire_fingerprint(
                    job.bucket, self.serve_cfg.pop_size, seed)
            snapshot_mod.verify_wire(wire, expect_fingerprint=expect)
            if (job.bucket is not None
                    and list(wire.get("bucket", ()))
                    != list(job.bucket)):
                raise snapshot_mod.SnapshotMismatch(
                    f"snapshot bucket {wire.get('bucket')} != routed "
                    f"bucket {list(job.bucket)}")
        except Exception as e:
            self.registry.counter("fleet.resume.rejected").inc()
            self._rec(jsonl.fault_entry, self.writer, "snapshot_ship",
                      "reject", e, 0, 0, 0, self.tracer.now(),
                      job=job.id)
            return False
        gens = int(wire.get("gens_done", 0))
        if not final and gens < job.snap_gens:
            return False               # never replace newer with older
        records = list(view.get("snapshot_records") or ())
        # the replica declares the prefix's byte size (it computed it
        # once, on its handler); the fallback re-measure covers a
        # mixed-version fleet
        rec_bytes = view.get("snapshot_records_bytes")
        if rec_bytes is None:
            rec_bytes = sum(len(json.dumps(r)) for r in records)
        # the (snap, snap_records, ...) tuple is read by job_view
        # handlers under jobs_lock: mutate it under the same lock so a
        # client can never see fence N's snapshot with fence N+1's
        # records (the replica-side ShipUnit consistency, kept here)
        with self.jobs_lock:
            job.snap = wire
            job.snap_records = records
            job.snap_gens = gens
            job.snap_truncated = bool(view.get("snapshot_truncated"))
            job.snap_bytes = int(wire.get("bytes", 0)) + int(rec_bytes)
        self._evict_snapshots()
        return True

    def _evict_snapshots(self) -> None:
        """Hold the cache under `--snapshot-hwm`: evict SETTLED jobs'
        snapshots first (a done base's final wire only warms future
        edits — losing it demotes those to a counted cold solve,
        never a lost resume), then OLDEST-PROGRESS (the snapshot
        whose loss wastes the least re-run). An evicted job fails
        over by replay — counted, never silent
        (`fleet.resume.evictions`; the jobs fall into
        `fleet.resume.replays` if their failover comes)."""
        with self.jobs_lock:
            cached = [j for j in self.jobs.values()
                      if j.snap is not None]
            total = sum(j.snap_bytes for j in cached)
            while total > self.cfg.snapshot_hwm and cached:
                victim = min(cached, key=lambda j: (
                    not (j.terminal() and j.records_final),
                    j.snap_gens, j.submitted_t))
                cached.remove(victim)
                total -= victim.snap_bytes
                victim.snap = None
                victim.snap_records = []
                victim.snap_bytes = 0
                victim.snap_gens = 0
                self.registry.counter("fleet.resume.evictions").inc()
        self.registry.gauge("fleet.resume.bytes").set(float(total))
        self.registry.gauge("fleet.resume.cached").set(
            float(len(cached)))

    def _on_death(self, handle, respawned: bool) -> None:
        """ReplicaSet prober callback (PROBER thread): only enqueue —
        router/job state is touched exclusively on the dispatcher.
        A respawned worker comes back cold, so its jobs fail over
        exactly like a dead one's (the handle stays live and may win
        them back)."""
        self.inbox.put(("failover", handle.name))

    def _failover(self, name: str) -> None:
        """A replica died (prober callback, via the inbox — so router
        state is only ever touched on this thread): forget its pins
        and warmth, then resubmit every unfinished job it owned.
        Idempotent by job id: the payload (id, seed, generation
        budget) replays verbatim, partial record tails are discarded,
        and the fresh solve's stream replaces them wholesale — the
        client observes exactly one completion with exactly one record
        stream. A job that COMPLETED on the dead replica but whose
        records the polls had not finished caching is replayed too:
        the stream is a pure function of the job, so the replay emits
        the identical records the lost copy held."""
        self.router.on_replica_dead(name)
        with self.jobs_lock:
            victims = [j for j in self.jobs.values()
                       if j.replica == name
                       and not (j.terminal() and j.records_final)]
        if self.flight is not None:
            # one stitched incident per failover: the gateway's own
            # rings + the dead replica's last bundle (live pull when
            # it still answers, the prober's cached copy otherwise) —
            # enqueued here, pulled and written on the RECORDER thread
            self.flight.trigger(f"failover:{name}", peers=[name])
        with self.tracer.span("failover", cat="fleet", replica=name,
                              jobs=len(victims),
                              flow=[j.flow for j in victims if j.flow]):
            for job in victims:
                self._reassign(job)

    def _reassign(self, job: GatewayJob) -> None:
        """One job's failover or preemption re-placement: RESUME when
        a fingerprint-valid snapshot is cached — the payload resends
        with the wire snapshot attached, the new replica admits it
        parked at the shipped progress, and the shipped record prefix
        joins this job's accumulated `prefix` so the settled stream is
        whole and duplicate-free (README "Fleet resume"). Without a
        cached snapshot the job REPLAYS exactly as before — unless a
        previously attached payload snapshot survives, which resumes
        from that older fence (deterministic lanes re-emit the lost
        middle identically, so the accumulated prefix stays valid).
        A pending cancel is honored either way (the replica that
        would have solved the rest is gone anyway)."""
        if job.cancel_requested:
            job.state = "cancelled"
            self._settle(job)
            return
        if job.snap is not None:
            # resume: consume the cached unit into payload + prefix
            # (under jobs_lock — job_view handlers read these fields).
            # A ship unit whose records carry an `admitted` jobEntry
            # came from an incarnation that REPLAYED from gen 0 (its
            # own resume was demoted) — those records are a complete
            # stream and REPLACE the accumulated prefix; appending
            # would duplicate every record the replay re-emitted.
            with self.jobs_lock:
                job.payload = dict(job.payload, snapshot=job.snap)
                fresh = any(
                    rec.get("jobEntry", {}).get("event") == "admitted"
                    for rec in job.snap_records)
                job.prefix = (list(job.snap_records) if fresh
                              else list(job.prefix)
                              + list(job.snap_records))
                job.prefix_truncated = (job.snap_truncated
                                        if fresh
                                        else job.prefix_truncated
                                        or job.snap_truncated)
                job.snap = None
                job.snap_records = []
                job.snap_bytes = 0
                # snap_gens is kept: it is the new incarnation's
                # starting progress — the fetch throttle's baseline
            self._evict_snapshots()    # republish the byte gauges
            self.registry.counter("fleet.resume.hits").inc()
            self._rec(self.tracer.record, "resume", self.now(), 0.0,
                      cat="fleet", job=job.id, flow=job.flow,
                      gens=job.snap_gens)
        elif (job.payload or {}).get("snapshot") is None:
            job.snap_gens = 0
            job.prefix = []
            job.prefix_truncated = False
            self.registry.counter("fleet.resume.replays").inc()
        job.records = []
        job.records_final = False
        job.records_truncated = False
        job.replica = None
        job.state = "accepted"
        job.extra_polls = 0
        job.place_started = self.now()       # fresh placement budget
        self.registry.counter("fleet.jobs_failed_over").inc()
        self._place(job)

    def _settle(self, job: GatewayJob) -> None:
        """A job is terminal AND its records are cached: final
        accounting, then retention — the payload (the whole `.tim`
        text, kept only for failover replay) is released, and settled
        jobs beyond `--retain-terminal` are evicted oldest-first (a
        long-running gateway must not hold every instance it ever
        served; an evicted id answers 404)."""
        job.records_final = True
        if job.finished_t is None:
            job.finished_t = self.now()
        # a settled job may still be named as an edit BASE (tt-edit):
        # keep just the inline instance (its edited form for an edit
        # job) — the bulk of the payload (attached snapshots, op
        # lists) is still released, and the basis leaves with the job
        # at --retain-terminal eviction
        bp = job.payload or {}
        basis = {k: bp[k] for k in ("tim", "problem", "n_days",
                                    "slots_per_day") if k in bp}
        if "tim" not in basis and "problem" not in basis:
            edited = (bp.get("edit") or {}).get("edited")
            basis = dict(edited) if isinstance(edited, dict) else None
        job.edit_basis = basis or None
        job.payload = None
        job.counts = None
        job.prefix = []
        if job.snap is not None:
            # a settled job needs no warm start; drop its cache share
            with self.jobs_lock:
                job.snap = None
                job.snap_records = []
                job.snap_bytes = 0
            self._evict_snapshots()    # republish the byte gauges
        if not job.counted:
            job.counted = True
            name = ("fleet.jobs_done" if job.state == "done"
                    else "fleet.jobs_failed")
            self.registry.counter(name).inc()
            latency = job.finished_t - job.submitted_t
            self.registry.histogram("fleet.job_seconds").observe(
                latency, exemplar={"job": job.id})
            self._slo_lat.append(latency)
            # the settle point on the job's chain: the instant state
            # and records publish together (zero-duration marker span)
            self._rec(self.tracer.record, "settle", self.now(), 0.0,
                      cat="fleet", job=job.id, flow=job.flow,
                      state=job.state, replica=job.replica,
                      latency_s=round(latency, 6))
        self._terminal_order.append(job.id)
        while len(self._terminal_order) > self.cfg.retain_terminal:
            evicted = self._terminal_order.pop(0)
            with self.jobs_lock:
                self.jobs.pop(evicted, None)

    def _fail(self, job: GatewayJob, reason: str) -> None:
        job.state = "failed"
        job.error = reason
        if job.prefix and not job.records:
            # what progress the dead incarnations did emit stays
            # visible on the failed view (honest partial stream)
            job.records = list(job.prefix)
            job.records_truncated = True
        self._settle(job)

    def _drain_tick(self) -> None:
        if not self.draining or self.drained.is_set():
            return
        with self.jobs_lock:
            active = [j for j in self.jobs.values()
                      if not (j.terminal() and j.records_final)]
        if active or not self.inbox.empty():
            return
        # every job settled AND its records are cached — only now may
        # owned replicas drain (they exit after draining; a replica
        # that exits before the gateway cached its tails would lose
        # them)
        if self.owned:
            self.replicas.stop_restarts()
            for handle in self.replicas.live():
                try:
                    handle.drain(timeout=self.cfg.probe_timeout)
                except Exception:
                    pass
        self.drained.set()


# ---------------------------------------------------------------- CLI


def main_fleet(argv) -> int:
    """`tt fleet` entry point (cli.py dispatches here). Runs until a
    POST /v1/drain (or SIGTERM/SIGINT, mapped to the same drain)
    completes."""
    import signal

    cfg = parse_fleet_args(argv)
    from timetabling_ga_tpu.fleet import replicas as replicas_mod
    if cfg.spawn:
        handles = replicas_mod.spawn_local(cfg)
    else:
        handles = [replicas_mod.ReplicaHandle(f"r{i}", url)
                   for i, url in enumerate(cfg.replicas)]
    gw = Gateway(cfg, handles, owned=bool(cfg.spawn))
    gw.start()
    print(f"# tt fleet: gateway on {gw.url} fronting "
          f"{len(handles)} replica(s): "
          f"{', '.join(h.url for h in handles)}",
          file=sys.stderr, flush=True)

    def _drain(signum, frame):
        print("# tt fleet: drain requested", file=sys.stderr,
              flush=True)
        gw.request_drain()

    signal.signal(signal.SIGTERM, _drain)
    signal.signal(signal.SIGINT, _drain)
    try:
        while not gw.drained.wait(0.5):
            pass
    finally:
        gw.close()
    return 0
