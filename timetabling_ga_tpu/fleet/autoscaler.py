"""tt-scale — the autoscaler: a policy-driven actuator over sustained
fleet signals.

ROADMAP item 3 built this loop's whole substrate across three PRs and
left one sentence open: "What remains is the ACTUATOR." The trigger
primitives are the obs/history.py window queries over the gateway's
own registry (`sustained(name, op, threshold, for_s)` — a spike that
visited a threshold once is not a sustained condition), the demand
side is the tt-meter `usage.tenant.<t>.*` counters those same rings
sample, and the lossless scale-down seam is the preempt drain + ship
+ resume-elsewhere path (README "Fleet resume"). This module is the
actuator: a die/hang-isolated control-loop thread ON the gateway that
evaluates a declarative policy every `--scale-every` seconds and acts
through the existing seams only —

  SPAWN   = the `--spawn` worker pool: fleet/replicas.spawn_one
            (fresh local port, `--boot-grace` covers the jax import),
            adopted into the prober/router via Gateway.adopt_replica.
  RETIRE  = Gateway.preempt_replica → POST /v1/drain?mode=preempt&
            replica=NAME: the victim parks + ships every job it owns
            and the dispatcher resumes them on the survivors, so
            scale-down is LOSSLESS BY CONSTRUCTION — no policy bug
            here can lose a job, only waste a warm cache.

The policy (all thresholds are FleetConfig `--scale-*` flags):

  scale UP (while live < --scale-max), first match wins:
    min_floor    live replicas fell below --scale-min (bypasses the
                 cooldown: a fleet below its floor heals NOW);
    queue_depth  sustained("serve.queue_depth", ">=",
                 --scale-up-queue, --scale-up-for) — the gateway's
                 active-job backlog held high for the whole window;
    slo_burn     sustained("fleet.slo_burn", ">=", 1, --scale-up-for)
                 — the --slo-p99 objective burning, not blinking;
    tenant_starved:<t>  rate("usage.tenant.<t>.queue_seconds",
                 --scale-up-for) >= --scale-starve-rate — a tenant's
                 queue wall growing faster than the fleet retires it
                 (the premium-tier starvation trigger; per-tenant
                 FLOP/s demand rides every decision as evidence).

  scale DOWN (while live > --scale-min):
    idle         sustained("serve.queue_depth", "<=",
                 --scale-down-queue, --scale-down-for), AND the
                 chosen victim individually shows
                 mean_over("fleet.replica.<n>.backlog",
                 --scale-idle-window) at/below the same threshold —
                 fleet-wide calm is necessary, per-replica idleness
                 picks who goes.

  WARMTH GUARD (the hard invariant): scale-down NEVER retires the
  only warm replica of a HOT bucket — a bucket with in-flight jobs or
  routed within --scale-warm-recent seconds. The router's pin/warmth
  maps are inputs, not suggestions: the dispatcher publishes a
  per-tick scale snapshot (Gateway._refresh_view) naming each
  replica's in-flight load and the sole-warm protections, and
  choose_victim() skips protected candidates (counted
  `fleet.scale.blocked_warmth`) before retiring the idlest cold one.

  COOLDOWN (--scale-cooldown): after any action, further actions are
  held (counted `fleet.scale.blocked_cooldown`) — an oscillating
  signal cannot flap the fleet faster than one action per cooldown.

Citizenship, like every prior layer:

  - every decision (actions AND blocks; idle ticks are silent) is a
    `scaleEntry` JSONL record on the gateway log with the
    sustained-window EVIDENCE that justified it — TIMING domain, so
    job record streams are bit-identical with the scaler on or off;
  - `fleet.scale.{ups,downs,blocked_warmth,blocked_cooldown,
    replicas_target,replicas_live}` live metrics, sampled by the same
    history rings the policy reads (the scaler observes itself);
  - a scale action triggers the flight recorder like a failover does
    (a retire carries the victim as a peer, so the stitched bundle
    holds the victim's final rings);
  - fault site `scaler` fires once per tick: a hung or dead scaler
    freezes the fleet at its current size — routing, dispatch,
    settlement, and writer drain never wait on it (the
    history/usage-ledger thread discipline; tests/test_scale.py);
  - `--scale-dry-run` evaluates and logs without acting, and
    `tt scale LOG` / `tt stats` render the decision log with its
    evidence.

Module-level imports are stdlib-only (runtime/faults + runtime/jsonl):
`tt scale` must run on any machine a gateway log was copied to.
"""

from __future__ import annotations

import json
import re
import sys
import threading
import warnings

from timetabling_ga_tpu.runtime import faults, jsonl

# usage.tenant.<t>.queue_seconds — the starvation trigger's series
# (obs/usage.py ledger counters, sampled by obs/history.py)
_TENANT_QUEUE_RE = re.compile(
    r"^usage\.tenant\.(?P<tenant>.+)\.queue_seconds$")
_TENANT_FLOPS_RE = re.compile(
    r"^usage\.tenant\.(?P<tenant>.+)\.flops$")

# per-tenant FLOP/s demand window (seconds): context evidence on every
# decision, per ROADMAP item 3's `rate("usage.tenant.acme.flops", 60)`
DEMAND_WINDOW_S = 60.0


def choose_victim(replicas: dict, protected: dict) -> tuple:
    """The scale-down victim among `replicas` ({name: {"inflight": n,
    "idle": bool, ...}} — dead/retired entries must already be
    filtered out), honoring the warmth guard: `protected` maps replica
    name -> the hot buckets it is the ONLY warm home of.

    Candidates must be individually idle (the mean-backlog guard the
    caller evaluated); preference is DEVICE-COLD first — a replica
    whose scraped `serve.resident_groups` gauge reads zero retires
    for free, while a warm one flushes every resident group back
    through the park path — then fewest `serve.resident_bytes` among
    the warm (smallest flush), then fewest in-flight jobs, then name
    (deterministic). A replica whose residency was never scraped
    (None) sorts with the warm ones: unknown is not known-cold.
    Pins deliberately do NOT drive the order: warmth protection is
    the correctness layer, and a cold bucket's re-warm after its idle
    home retires is a bounded warm-up cost, not a lost job. Returns
    (victim_name_or_None, [names the warmth guard skipped]) — a
    skipped name means the policy WANTED that replica and the guard
    refused (`fleet.scale.blocked_warmth`)."""
    def _key(n):
        v = replicas[n]
        rg = v.get("resident_groups")
        rb = v.get("resident_bytes")
        return (0 if rg == 0 else 1,
                rb if isinstance(rb, (int, float)) else float("inf"),
                v.get("inflight", 0), n)
    order = sorted(
        (name for name, v in replicas.items() if v.get("idle")),
        key=_key)
    skipped = []
    for name in order:
        if protected.get(name):
            skipped.append(name)
            continue
        return name, skipped
    return None, skipped


class AutoScaler:
    """The gateway's scaling control loop: one daemon thread, one
    policy evaluation per `--scale-every` seconds (`tick()` is the
    testable unit), actuating ONLY through the spawn pool and the
    preempt-drain seam. The thread never touches router or job state
    directly — it reads the dispatcher's published scale snapshot and
    the history ring, both lock-guarded, and its actuations are an
    inbox enqueue (preempt) plus a subprocess spawn + handle adoption
    (both designed for off-dispatcher callers). tt-analyze TT608 pins
    this as the ONLY legal actuation site."""

    def __init__(self, gw, cfg, spawn_fn=None, now=None):
        self._gw = gw
        self._cfg = cfg
        self._now = now or gw.now
        self._spawn_fn = spawn_fn    # name -> ReplicaHandle; None =
        #                              nothing to grow (dry-run, or a
        #                              static fleet being evaluated)
        self._last_action_t = None   # cooldown anchor
        self._last_emitted = None    # (action, reason, blocked) of
        #                              the last record: a sustained
        #                              block emits ONE record per
        #                              stretch, not one per tick
        self._spawn_seq = 0
        self._tick_errored = False   # warn once per failure stretch
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="tt-scale", daemon=True)
        # pre-create the decision counters/gauges so the history ring
        # samples the families from tick one (a trigger that fires on
        # a series born mid-window would otherwise lack coverage)
        reg = gw.registry
        for name in ("ups", "downs", "blocked_warmth",
                     "blocked_cooldown", "tick_errors"):
            reg.counter(f"fleet.scale.{name}")
        reg.gauge("fleet.scale.replicas_target")
        reg.gauge("fleet.scale.replicas_live")

    # -- lifecycle (the history-sampler discipline) ----------------------

    def start(self) -> "AutoScaler":
        self._thread.start()
        return self

    def alive(self) -> bool:
        return self._thread.is_alive()

    def close(self) -> None:
        self._stop.set()
        if self._thread.ident is not None:   # never-started: no join
            self._thread.join(timeout=2.0)   # a hung scaler is
            #                                  abandoned (daemon)

    def _loop(self) -> None:
        while True:
            if not self.tick():
                return
            if self._stop.wait(self._cfg.scale_every):
                return

    # -- one evaluation --------------------------------------------------

    def tick(self) -> bool:
        """One policy evaluation + (maybe) one actuation; False when
        the thread should exit (injected death / teardown). Any other
        failure skips the tick — a torn snapshot or a failed spawn
        must degrade to 'no scaling this second', never kill the
        loop or stall anything else."""
        if sys.is_finalizing():
            return False
        try:
            faults.maybe_fail("scaler")
            decision = self.evaluate()
            self._apply(decision)
        except SystemExit:
            return False            # injected death: exit silently
        except Exception as e:
            # the honest-degradation discipline (usage.dropped, the
            # flight rings' *_dropped): a failing tick freezes the
            # fleet at its current size, and an empty decision log
            # under sustained load must be distinguishable from calm
            # — count it, and warn once per failure stretch
            try:
                self._gw.registry.counter(
                    "fleet.scale.tick_errors").inc()
                if not self._tick_errored:
                    self._tick_errored = True
                    warnings.warn(
                        f"tt-scale: tick failed ({e!r}); scaling is "
                        "frozen until a tick succeeds (counting "
                        "fleet.scale.tick_errors)", RuntimeWarning)
            except Exception:
                pass
            return True
        self._tick_errored = False
        return True

    def _live(self) -> list:
        """Handles the policy counts as serving capacity: not dead,
        not already retired by an earlier decision (a retiring worker
        is still draining, but it is no longer capacity)."""
        return [h for h in self._gw.replicas.all()
                if not h.dead and not getattr(h, "retired", False)]

    def evaluate(self):
        """The pure policy decision: a dict proposal (the scaleEntry
        body shape minus actuation results), or None for a silent
        idle tick. Reads ONLY the history ring and the dispatcher's
        scale snapshot — no job table, no router internals."""
        gw, cfg = self._gw, self._cfg
        h = gw.history
        live = self._live()
        n_live = len(live)
        live_names = {x.name for x in live}
        demand = self._tenant_demand(h)

        # -- spawn triggers (first match wins) --------------------------
        if n_live < cfg.scale_min:
            return {"action": "up", "reason": "min_floor",
                    "evidence": {"live": n_live,
                                 "scale_min": cfg.scale_min}}
        if n_live < cfg.scale_max:
            if h.sustained("serve.queue_depth", ">=",
                           cfg.scale_up_queue, cfg.scale_up_for):
                ev = {"serve.queue_depth": {
                    "op": ">=", "threshold": cfg.scale_up_queue,
                    "for_s": cfg.scale_up_for,
                    "mean": h.mean_over("serve.queue_depth",
                                        cfg.scale_up_for)}}
                if demand:
                    ev["demand_flops_per_s"] = demand
                return {"action": "up", "reason": "queue_depth",
                        "evidence": ev}
            if h.sustained("fleet.slo_burn", ">=", 1.0,
                           cfg.scale_up_for):
                return {"action": "up", "reason": "slo_burn",
                        "evidence": {"fleet.slo_burn": {
                            "op": ">=", "threshold": 1.0,
                            "for_s": cfg.scale_up_for},
                            **({"demand_flops_per_s": demand}
                               if demand else {})}}
            starved = self._starved_tenant(h)
            if starved is not None:
                tenant, rate = starved
                ev = {f"usage.tenant.{tenant}.queue_seconds": {
                    "rate_per_s": round(rate, 6),
                    "threshold": cfg.scale_starve_rate,
                    "window_s": cfg.scale_up_for}}
                if demand:
                    ev["demand_flops_per_s"] = demand
                return {"action": "up",
                        "reason": f"tenant_starved:{tenant}",
                        "evidence": ev}

        # -- scale-down guard -------------------------------------------
        if (n_live > cfg.scale_min
                and h.sustained("serve.queue_depth", "<=",
                                cfg.scale_down_queue,
                                cfg.scale_down_for)):
            snap = gw.scale_snapshot() or {}
            reps = {}
            for name, v in (snap.get("replicas") or {}).items():
                if name not in live_names:
                    continue         # snapshot lags adoption/retire
                mean = h.mean_over(f"fleet.replica.{name}.backlog",
                                   cfg.scale_idle_window)
                reps[name] = dict(
                    v, backlog_mean=mean,
                    # an unwatched backlog (never probed, or a
                    # replica younger than its first sample) is NOT
                    # idle — the ring answers with evidence or the
                    # guard answers no
                    idle=(mean is not None
                          and mean <= cfg.scale_down_queue))
            protected = {k: v for k, v
                         in (snap.get("protected") or {}).items()
                         if k in reps}
            victim, skipped = choose_victim(reps, protected)
            ev = {"serve.queue_depth": {
                "op": "<=", "threshold": cfg.scale_down_queue,
                "for_s": cfg.scale_down_for,
                "mean": h.mean_over("serve.queue_depth",
                                    cfg.scale_down_for)},
                "replicas": {n: {"inflight": v.get("inflight", 0),
                                 "backlog_mean": v.get("backlog_mean"),
                                 "idle": v.get("idle", False),
                                 "resident_groups":
                                     v.get("resident_groups"),
                                 "resident_bytes":
                                     v.get("resident_bytes")}
                             for n, v in reps.items()}}
            if skipped:
                ev["warmth_skipped"] = {
                    n: protected.get(n, []) for n in skipped}
            return {"action": "down", "reason": "idle",
                    "replica": victim, "warmth_skipped": skipped,
                    "evidence": ev}
        return None

    def _tenant_demand(self, h) -> dict:
        """Per-tenant FLOP/s over the last DEMAND_WINDOW_S — ROADMAP
        item 3's demand curve, attached to every decision as
        evidence (never a trigger by itself)."""
        demand = {}
        for name in h.names():
            m = _TENANT_FLOPS_RE.match(name)
            if m is None:
                continue
            r = h.rate(name, DEMAND_WINDOW_S)
            if r is not None and r > 0:
                demand[m.group("tenant")] = round(r, 3)
        return demand

    def _starved_tenant(self, h):
        """(tenant, rate) of the worst queue_seconds growth at/above
        --scale-starve-rate, or None. queue_seconds is a cumulative
        counter: its RATE is how many seconds of queue wall the
        tenant accrues per wall second — >= 1.0 means jobs queue
        faster than they start."""
        cfg = self._cfg
        if cfg.scale_starve_rate <= 0:
            return None
        worst = None
        for name in h.names():
            m = _TENANT_QUEUE_RE.match(name)
            if m is None:
                continue
            r = h.rate(name, cfg.scale_up_for)
            if r is not None and r >= cfg.scale_starve_rate:
                if worst is None or r > worst[1]:
                    worst = (m.group("tenant"), r)
        return worst

    # -- actuation -------------------------------------------------------

    def _apply(self, decision) -> None:
        gw, cfg = self._gw, self._cfg
        n_live = len(self._live())
        reg = gw.registry
        reg.gauge("fleet.scale.replicas_live").set(float(n_live))
        if decision is None:
            reg.gauge("fleet.scale.replicas_target").set(
                float(min(max(n_live, cfg.scale_min), cfg.scale_max)))
            self._last_emitted = None     # a calm tick re-arms the
            #                               one-record-per-stretch latch
            return
        now = self._now()
        action = decision["action"]
        # cooldown hysteresis (min_floor heals regardless)
        if (decision["reason"] != "min_floor"
                and self._last_action_t is not None
                and cfg.scale_cooldown > 0
                and now - self._last_action_t < cfg.scale_cooldown):
            reg.counter("fleet.scale.blocked_cooldown").inc()
            self._emit(decision, n_live, blocked="cooldown")
            return
        if action == "down":
            for _ in decision.get("warmth_skipped", ()):
                reg.counter("fleet.scale.blocked_warmth").inc()
            if decision.get("replica") is None:
                # every candidate protected or not-idle: the guard
                # held the whole action
                self._emit(decision, n_live, blocked="warmth"
                           if decision.get("warmth_skipped")
                           else "no_idle_victim")
                return
            if not cfg.scale_dry_run:
                self._retire(decision["replica"])
            reg.counter("fleet.scale.downs").inc()
            self._done(decision, n_live, n_live - 1, now)
            return
        # action == "up"
        target = min(n_live + 1, cfg.scale_max)
        name = None
        if not cfg.scale_dry_run:
            if self._spawn_fn is None:
                self._emit(decision, n_live, blocked="no_pool")
                return
            name = self._next_name()
            handle = self._spawn_fn(name)
            gw.adopt_replica(handle)
        reg.counter("fleet.scale.ups").inc()
        self._done(dict(decision, replica=name), n_live, target, now)

    def _retire(self, name: str) -> None:
        """Lossless scale-down: mark the handle retired (the prober
        will not respawn its expected exit) and preempt-drain it —
        the victim parks + ships, the dispatcher resumes its jobs on
        the survivors (README "Fleet resume")."""
        handle = self._gw.replicas.get(name)
        if handle is not None:
            handle.retired = True
        self._gw.preempt_replica(name)

    def _next_name(self) -> str:
        taken = {h.name for h in self._gw.replicas.all()}
        while f"s{self._spawn_seq}" in taken:
            self._spawn_seq += 1
        name = f"s{self._spawn_seq}"
        self._spawn_seq += 1
        return name

    def _done(self, decision, live, target, now) -> None:
        self._last_action_t = now
        self._last_emitted = None
        reg = self._gw.registry
        reg.gauge("fleet.scale.replicas_target").set(float(target))
        # re-publish live AFTER the actuation: an adoption/retire this
        # tick is visible on the gauge this tick
        reg.gauge("fleet.scale.replicas_live").set(
            float(len(self._live())))
        flight = getattr(self._gw, "flight", None)
        if flight is not None and not self._cfg.scale_dry_run:
            try:
                # a scale action is an incident-bundle trigger peer of
                # failover/burn: a retire pulls the victim's final
                # bundle into the stitched record (enqueue only — the
                # pull runs on the RECORDER thread)
                peers = ([decision["replica"]]
                         if decision["action"] == "down"
                         and decision.get("replica") else [])
                flight.trigger(
                    f"scale_{decision['action']}", peers=peers)
            except Exception:
                pass
        self._emit(decision, live, target=target, acted=True)

    # -- the decision log ------------------------------------------------

    def _emit(self, decision, live, blocked=None, target=None,
              acted=False) -> None:
        """One scaleEntry on the gateway log (via the gw_writer
        isolation guard — a dead log writer never stalls scaling).
        Actions always emit; a sustained BLOCK emits once per stretch
        (the latch re-arms on any action or calm tick), so a 1 Hz
        scaler inside a 60 s cooldown writes one record, not sixty."""
        key = (decision["action"], decision["reason"], blocked)
        if not acted:
            if key == self._last_emitted:
                return
            self._last_emitted = key
        gw = self._gw
        extra = {"live": int(live),
                 "dry_run": bool(self._cfg.scale_dry_run)}
        if target is not None:
            extra["target"] = int(target)
        if blocked is not None:
            extra["blocked"] = blocked
        if decision.get("replica") is not None:
            extra["replica"] = decision["replica"]
        if decision.get("evidence"):
            extra["evidence"] = decision["evidence"]
        gw._rec(jsonl.scale_entry, gw.writer, decision["action"],
                decision["reason"], ts=gw.tracer.now(), **extra)


# ---------------------------------------------------------------- report


def summarize_entries(records) -> str:
    """The `tt scale` / `tt stats == scale` report over scaleEntry
    records: the decision log with its sustained-window evidence,
    plus action/block tallies."""
    entries = [r["scaleEntry"] for r in records if "scaleEntry" in r]
    if not entries:
        return "== scale: no scaleEntry records"
    lines = [f"== scale decisions ({len(entries)} records)"]
    tallies: dict = {}
    for e in entries:
        kind = (f"blocked_{e['blocked']}" if e.get("blocked")
                else e.get("action", "?"))
        tallies[kind] = tallies.get(kind, 0) + 1
        ts = e.get("ts")
        head = f"  {ts:.1f}s" if isinstance(ts, (int, float)) else "  -"
        what = (f"{e.get('action')} ({e.get('reason')})"
                + (f" BLOCKED:{e['blocked']}" if e.get("blocked")
                   else ""))
        parts = [head, what]
        if e.get("replica"):
            sign = "-" if e.get("action") == "down" else "+"
            parts.append(f"{sign}{e['replica']}")
        if e.get("live") is not None:
            tgt = (f"->{e['target']}" if e.get("target") is not None
                   else "")
            parts.append(f"live {e['live']}{tgt}")
        if e.get("dry_run"):
            parts.append("[dry-run]")
        lines.append(" ".join(parts))
        for line in _evidence_lines(e.get("evidence") or {}):
            lines.append("      " + line)
    lines.append("  " + "  ".join(f"{k}:{v}"
                                  for k, v in sorted(tallies.items())))
    return "\n".join(lines)


def _evidence_lines(ev: dict) -> list:
    """Render one decision's evidence dict: the window queries that
    justified it, one per line."""
    out = []
    for name, v in sorted(ev.items()):
        if name == "demand_flops_per_s" and isinstance(v, dict):
            flat = " ".join(f"{t}:{r:g}" for t, r in sorted(v.items()))
            out.append(f"demand flop/s: {flat}")
        elif name == "replicas" and isinstance(v, dict):
            def _res(d):
                rg = d.get("resident_groups")
                if rg is None:
                    return ""
                if rg == 0:
                    return ", cold"
                rb = d.get("resident_bytes")
                return (f", {rg:g} resident"
                        + (f" ({rb:g}B)" if rb is not None else ""))
            flat = " ".join(
                f"{n}(inflight {d.get('inflight', 0)}, "
                f"mean backlog "
                f"{d.get('backlog_mean') if d.get('backlog_mean') is not None else '?'}"
                f"{', idle' if d.get('idle') else ''}{_res(d)})"
                for n, d in sorted(v.items()))
            out.append(f"victims considered: {flat}")
        elif name == "warmth_skipped" and isinstance(v, dict):
            flat = "; ".join(f"{n} sole-warm for {b}"
                             for n, b in sorted(v.items()))
            out.append(f"warmth guard: {flat}")
        elif isinstance(v, dict) and "op" in v:
            mean = (f", window mean {v['mean']:g}"
                    if isinstance(v.get("mean"), (int, float))
                    else "")
            out.append(f"{name} {v['op']} {v['threshold']:g} "
                       f"sustained {v['for_s']:g}s{mean}")
        elif isinstance(v, dict) and "rate_per_s" in v:
            out.append(f"{name} rate {v['rate_per_s']:g}/s >= "
                       f"{v['threshold']:g} over {v['window_s']:g}s")
        else:
            out.append(f"{name}: {v}")
    return out


def main_scale(argv) -> int:
    """`tt scale <gateway.jsonl> [more.jsonl ...]` — render the
    autoscaler's decision log (stdlib + jax-free, like tt stats)."""
    inputs = []
    as_json = False
    for a in argv:
        if a in ("-h", "--help"):
            print("usage: tt scale <gateway.jsonl> [more.jsonl ...] "
                  "[--json]\n\n"
                  "summarize the tt-scale decision log: every "
                  "scaleEntry with the sustained-window evidence that "
                  "justified it (spawn triggers, idle guards, warmth "
                  "blocks, cooldown holds), plus action tallies")
            return 0
        if a == "--json":
            as_json = True
        elif a.startswith("-"):
            raise SystemExit(f"unknown argument: {a}")
        else:
            inputs.append(a)
    if not inputs:
        raise SystemExit("usage: tt scale <gateway.jsonl> "
                         "[more.jsonl ...] [--json]")
    records = []
    for path in inputs:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except ValueError:
                    continue        # torn tail line of a live log
    if as_json:
        print(json.dumps([r["scaleEntry"] for r in records
                          if "scaleEntry" in r], indent=2))
        return 0
    print(summarize_entries(records))
    return 0


if __name__ == "__main__":
    sys.exit(main_scale(sys.argv[1:]))
