"""tt-fleet: the HTTP solve front and N-replica router (README
"Fleet"; ROADMAP item 3).

Layers:

  gateway.py   the shared `/v1` HTTP API (solve / jobs / cancel /
               drain) spoken by BOTH the gateway and every replica,
               and the Gateway itself: accept-and-enqueue handlers, a
               dispatcher thread that owns every piece of outbound
               I/O (routing, submission, status polls, failover), and
               a cached job table the handlers serve reads from.
  router.py    the bucket-affine router: jobs land where their shape
               bucket's lane programs are already compiled, driven by
               each replica's /readyz reasons, backlog gauge, and
               measured compile-hit rate.
  replicas.py  replica-set management: the drive loop that turns a
               SolveService into an HTTP replica (in-process or
               `tt serve --http` foreground), spawned local worker
               processes, liveness probing with restart-on-death, and
               graceful drain.
  client.py    `tt submit` — the stdlib HTTP client.

Import discipline: the gateway never touches a device — it routes on
`.tim` headers and scraped gauges (serve/bucket.py's key math only);
the solver stack enters a process exclusively through the replica
drive loop's deferred imports. `tt submit` (client.py) is pure stdlib
— it runs on machines with no accelerator stack at all.
"""

from timetabling_ga_tpu.fleet.router import NoReplicaError, Router

__all__ = ["Router", "NoReplicaError"]
