"""The bucket-affine router: send each job where its programs live.

A replica's expensive asset is its compile cache: every (bucket,
program) pair it has served cost it a multi-second XLA compile, and a
warm bucket serves any same-bucket instance with zero compiles
(serve/bucket.py). A router that sprays jobs round-robin pays that
compile on EVERY replica per bucket; a bucket-affine router pays it
once per bucket fleet-wide, then keeps landing that bucket's jobs on
the replica that already owns the programs.

Routing inputs — exactly the signals ROADMAP item 3 names, all
refreshed by the ReplicaSet's probe thread (fleet/replicas.py), never
fetched on the routing path itself:

  /readyz      structured reasons (`backlog_full`, `near_hbm_limit`,
               `stalled`, `draining`, ...): a not-ready replica keeps
               its pins but receives no NEW work while any reason is
               up — except when every live replica is not-ready, in
               which case the least-loaded one is used anyway
               (admission control downstream is the real gate, and
               parking a job at the gateway forever helps nobody);
  backlog      the `serve.queue_depth` gauge scraped from /metrics —
               the load component of the placement score;
  compile-hit  the measured `compile.{count,cache_hits}` families from
               /metrics: when a bucket must be placed fresh, prefer
               the replica whose cache already absorbs most of its
               traffic (a high hit rate means adding one more bucket
               costs it least marginal compile churn).

Affinity bookkeeping distinguishes three outcomes per routing:

  hit      the chosen replica is already WARM for the bucket (it
           served it before) — the steady state;
  warm-up  the bucket's FIRST landing anywhere in the fleet — the
           unavoidable once-per-bucket compile bill, excluded from
           the rate;
  miss     a cold landing of a bucket the fleet has already served
           somewhere — the job DETOURED off its warm home (not-ready
           probe, failover exclusion) or the pin moved after a
           death, so a second replica now pays a compile the
           affinity policy exists to avoid. A detour never moves the
           pin: the warm programs still live on the home, and the
           bucket returns there the moment it probes ready again.

`hit_rate()` = hits / (hits + misses): the fraction of post-warm-up
routings that landed warm — the number the bench's `extra.fleet` leg
and the acceptance test measure (>= 0.9 after warm-up on a stable
fleet; a mid-stream replica death shows up here as misses, one per
repinned bucket).

Stdlib-only, single-threaded by design: only the gateway's dispatcher
thread calls `route`, the same thread that handles failover — no lock,
no torn affinity map.
"""

from __future__ import annotations

from timetabling_ga_tpu.runtime import faults


class NoReplicaError(RuntimeError):
    """No live replica can take the job (all dead or excluded)."""


class Router:
    """Bucket -> replica placement with affinity, scoring, failover.

    `registry` (optional, the gateway's MetricsRegistry) receives the
    routing counters as real families — `fleet.route.{hit,warm,miss}`
    and `fleet.route.repins` — so the affinity story `/v1/fleet` tells
    in JSON is also on `/metrics` (the JSON view is derivable from the
    metric families; fleet/gateway.py docstring documents the
    mapping). `last_decision` is the most recent placement's score
    inputs (outcome, backlog, pin count, measured compile-hit rate):
    the gateway reads it right after `route()` — same thread, no race
    — to emit the `routeEntry` record."""

    def __init__(self, replica_set, registry=None):
        self._set = replica_set
        self._metrics = registry
        self._pins: dict = {}        # bucket -> replica name
        self._warm: dict = {}        # replica name -> set of buckets
        self._seen: set = set()      # buckets routed at least once
        self.pin_counts: dict = {}   # replica name -> pinned buckets
        #                              (maintained at every pin move so
        #                              the per-replica `pins` gauge is
        #                              an atomic dict read, never an
        #                              iteration racing this thread)
        self.last_decision: dict = {}
        self.routed = 0
        self.hits = 0                # landed on an already-warm home
        self.warmups = 0             # a bucket's fleet-wide first land
        self.misses = 0              # cold landing of a known bucket
        self.repins = 0              # pin MOVED (home left the live
        #                              set); a transient detour is a
        #                              miss but never a repin

    # -- the decision ---------------------------------------------------

    def route(self, bucket: tuple, exclude: tuple = ()):
        """Pick the replica for one job of `bucket`. Deterministic
        given the probe state; raises NoReplicaError when nothing live
        remains. `exclude` removes replicas this job already failed on
        (failover must not bounce a job back to its dead home)."""
        # fault-injection point (runtime/faults.py `route` site): an
        # injected hang/die parks/ends the gateway's dispatcher thread
        # — replica dispatch loops and writer drains never wait on it
        # (tests/test_fleet.py pins the isolation)
        faults.maybe_fail("route")
        live = [h for h in self._set.live() if h.name not in exclude]
        if not live:
            raise NoReplicaError(
                f"no live replica for bucket {bucket} "
                f"(excluded: {list(exclude)})")
        ready = [h for h in live if h.ready]
        pool = ready or live     # degraded fleet: least-bad placement
        pinned = self._pins.get(bucket)
        if pinned is not None:
            handle = next((h for h in pool if h.name == pinned), None)
            if handle is not None:
                return self._account(bucket, handle)
            # the home is unusable RIGHT NOW. If it is still in the
            # live set — merely not-ready, or excluded for THIS job
            # by a failover — the job detours but the PIN STAYS: a
            # single backlog_full probe (or one refused send) must
            # not migrate a bucket whose warm programs still live
            # there. Only a home gone from the live set entirely
            # (death-callback race) moves the pin here; outright
            # deaths clear their pins in on_replica_dead.
            fallback = min(pool, key=self._score)
            if not any(h.name == pinned
                       for h in self._set.live()):
                self._set_pin(bucket, fallback.name)
                self.repins += 1
                if self._metrics is not None:
                    self._metrics.counter("fleet.route.repins").inc()
            return self._account(bucket, fallback)
        handle = min(pool, key=self._score)
        self._set_pin(bucket, handle.name)
        return self._account(bucket, handle)

    def _set_pin(self, bucket: tuple, name: str) -> None:
        old = self._pins.get(bucket)
        if old == name:
            return
        if old is not None:
            self.pin_counts[old] = max(0, self.pin_counts.get(old, 1)
                                       - 1)
        self._pins[bucket] = name
        self.pin_counts[name] = self.pin_counts.get(name, 0) + 1

    def _account(self, bucket: tuple, handle):
        """Affinity bookkeeping for one placement (module docstring:
        hit / warm-up / miss) + the routing counters and the
        `last_decision` score-input snapshot the gateway's routeEntry
        record reads."""
        warm = bucket in self._warm.setdefault(handle.name, set())
        self.routed += 1
        if warm:
            outcome = "hit"
            self.hits += 1
        elif bucket in self._seen:
            outcome = "miss"       # known bucket forced onto a cold
            self.misses += 1       # replica — the affinity failure mode
            self._warm[handle.name].add(bucket)
        else:
            outcome = "warm"       # unavoidable once-per-bucket compile
            self.warmups += 1
            self._warm[handle.name].add(bucket)
        self._seen.add(bucket)
        if self._metrics is not None:
            self._metrics.counter(f"fleet.route.{outcome}").inc()
        self.last_decision = {
            "outcome": outcome, "replica": handle.name,
            "backlog": handle.queue_depth,
            "pins": self.pin_counts.get(handle.name, 0),
            "compile_hit_rate": round(handle.compile_hit_rate(), 4)}
        return handle

    def _score(self, handle) -> tuple:
        """Placement score for a bucket with no usable pin: fewest
        queued jobs first (the backlog gauge), then fewest pinned
        buckets (spread fresh buckets across the fleet even before
        the load gauges move — probes refresh at probe cadence, jobs
        can arrive faster), then the WARMEST cache (measured
        compile-hit rate — adding a bucket there costs the least
        marginal compile churn), then name for determinism."""
        depth = handle.queue_depth
        if depth is None or depth != depth:
            depth = 0.0
        pinned_here = sum(1 for r in self._pins.values()
                          if r == handle.name)
        return (depth, pinned_here, -handle.compile_hit_rate(),
                handle.name)

    # -- failover hooks -------------------------------------------------

    def on_replica_dead(self, name: str) -> None:
        """Forget a dead replica: its pins move on their next routing
        (counted as repins there) and its warm set is gone — a
        restarted process starts cold."""
        self._warm.pop(name, None)
        for bucket in [b for b, r in self._pins.items() if r == name]:
            del self._pins[bucket]
        self.pin_counts[name] = 0

    def sole_warm_owner(self, bucket: tuple, live_names) -> str | None:
        """The ONE live replica warm for `bucket`, or None when zero
        or several are — the tt-scale warmth guard's input
        (fleet/autoscaler.py): scale-down must never retire a hot
        bucket's only warm home. Dispatcher-thread only, like every
        other read of the warmth map."""
        owners = [n for n in live_names
                  if bucket in self._warm.get(n, ())]
        return owners[0] if len(owners) == 1 else None

    # -- accounting -----------------------------------------------------

    def hit_rate(self) -> float:
        """Post-warm-up affinity: of the routings that COULD have
        landed warm (everything but each bucket's fleet-wide first),
        the fraction that did."""
        eligible = self.hits + self.misses
        return self.hits / eligible if eligible > 0 else 1.0

    def stats(self) -> dict:
        return {"routed": self.routed, "affinity_hits": self.hits,
                "warmups": self.warmups, "misses": self.misses,
                "repins": self.repins,
                "affinity_hit_rate": round(self.hit_rate(), 4),
                "pins": {str(list(b)): r
                         for b, r in sorted(self._pins.items())}}
