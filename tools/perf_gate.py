"""Perf-regression gate: fresh bench numbers vs the committed history.

Compares a freshly produced `python bench.py` JSON (or a driver-style
`{"n", "cmd", "rc", "tail", "parsed"}` capture of one) against the
newest committed `BENCH_r0N.json` round and FAILS (exit 1) when a gated
metric regressed beyond its tolerance band — the check that turns the
perf history from a post-hoc table (tools/bench_report.py) into a
merge-time gate (`tools/ci_check.sh --perf`).

Gated metrics and directions:

    gens/s parallel     higher is better   (headline throughput)
    gens/s scan         higher is better
    ms/gen sweep128     lower  is better   (sweep LS latency)
    soak jobs/min       higher is better   (serve throughput)

Both sides go through bench_report's salvage ladder (parsed ->
tail-JSON -> regex), so a truncated capture still gates on whatever
metrics survived; a metric missing on EITHER side is reported and
skipped, never silently passed off as a comparison. The tolerance band
is deliberately wide by default (25%): CPU bench numbers jitter with
host load, and a gate that cries wolf gets deleted — it exists to
catch the 2x cliffs (a lost jit cache, an accidental host sync per
generation), not 3% noise.

    python tools/perf_gate.py fresh.json                 # vs newest round
    python tools/perf_gate.py fresh.json --baseline BENCH_r04.json
    python tools/perf_gate.py fresh.json --tolerance 0.15
    python tools/perf_gate.py fresh.json --json          # machine-readable

Stdlib-only and device-free, like every tools/ reader.
"""

from __future__ import annotations

import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_report import (  # noqa: E402
    REPO, _METRICS, _decode_tail_json, _metric, load_bench_round)

# (bench_report column header, direction). direction +1: higher is
# better (throughput); -1: lower is better (latency).
GATED = [
    ("gens/s parallel", +1),
    ("gens/s scan", +1),
    ("ms/gen sweep128", -1),
    ("soak jobs/min", +1),
]

DEFAULT_TOLERANCE = 0.25


def extract_metrics(path: str) -> dict:
    """bench_report-header -> value for one bench result file.

    Accepts either a raw `python bench.py` JSON document or a driver
    capture wrapper around one; both run the same salvage ladder so the
    gate never depends on the capture having been clean.
    """
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and ("tail" in doc or "parsed" in doc):
        return load_bench_round(path)["metrics"]
    if not isinstance(doc, dict):
        doc = _decode_tail_json(text)
    metrics = {}
    for header, leg, key in _METRICS:
        v = _metric(doc, text, leg, key)
        if v is not None:
            metrics[header] = v
    return metrics


def newest_baseline(root: str = REPO):
    rounds = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
    return rounds[-1] if rounds else None


def check(fresh: dict, base: dict,
          tolerance: float = DEFAULT_TOLERANCE) -> list:
    """Compare gated metrics; one result row per gate.

    A row is a dict {metric, base, fresh, change, status} where
    `change` is the signed relative change in the GOOD direction
    (+0.10 = 10% better, -0.30 = 30% worse) and status is "ok",
    "regression", or "skipped" (metric missing on either side).
    """
    rows = []
    for name, direction in GATED:
        b, f = base.get(name), fresh.get(name)
        if b is None or f is None or b == 0:
            rows.append({"metric": name, "base": b, "fresh": f,
                         "change": None, "status": "skipped"})
            continue
        change = direction * (f - b) / abs(b)
        rows.append({"metric": name, "base": b, "fresh": f,
                     "change": change,
                     "status": ("regression" if change < -tolerance
                                else "ok")})
    return rows


def render(rows: list, tolerance: float) -> str:
    lines = [f"== perf gate (tolerance {tolerance:.0%})"]
    for r in rows:
        if r["status"] == "skipped":
            lines.append(f"  {r['metric']:<18} skipped "
                         f"(base={r['base']} fresh={r['fresh']})")
        else:
            lines.append(
                f"  {r['metric']:<18} base {r['base']:<10.4g} "
                f"fresh {r['fresh']:<10.4g} "
                f"{r['change']:+.1%}  {r['status'].upper()}")
    bad = [r for r in rows if r["status"] == "regression"]
    compared = [r for r in rows if r["status"] != "skipped"]
    if not compared:
        lines.append("  NO metrics comparable — gate cannot pass "
                     "vacuously")
    lines.append("  verdict: " + ("REGRESSION" if bad or not compared
                                  else "pass"))
    return "\n".join(lines)


def main(argv) -> int:
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    tolerance = DEFAULT_TOLERANCE
    if "--tolerance" in argv:
        i = argv.index("--tolerance")
        tolerance = float(argv[i + 1])
        del argv[i:i + 2]
    baseline = None
    if "--baseline" in argv:
        i = argv.index("--baseline")
        baseline = argv[i + 1]
        del argv[i:i + 2]
    if not argv:
        print("usage: perf_gate.py <fresh-bench.json> "
              "[--baseline BENCH_r0N.json] [--tolerance F] [--json]",
              file=sys.stderr)
        return 2
    fresh_path = argv[0]
    if baseline is None:
        baseline = newest_baseline()
        if baseline is None:
            print("perf_gate: no committed BENCH_r*.json baseline",
                  file=sys.stderr)
            return 2
    try:
        fresh = extract_metrics(fresh_path)
        base = extract_metrics(baseline)
    except OSError as e:
        print(f"perf_gate: {e}", file=sys.stderr)
        return 2
    rows = check(fresh, base, tolerance)
    compared = [r for r in rows if r["status"] != "skipped"]
    bad = [r for r in rows if r["status"] == "regression"]
    ok = bool(compared) and not bad
    if as_json:
        print(json.dumps({"baseline": os.path.basename(baseline),
                          "fresh": os.path.basename(fresh_path),
                          "tolerance": tolerance, "rows": rows,
                          "ok": ok}, indent=2))
    else:
        print(f"baseline: {baseline}")
        print(render(rows, tolerance))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
