"""One-off tuning probe: TPU-only quality-at-budget on one instance for a
grid of (pop, sweeps, swap_block, migration_period) configs, using the
race harness's exact warm/timed flow. Emits one JSON line per config.

Usage: python tools/tune_probe.py <instance> <budget> [seed]
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.quality_race import make_instances, run_tpu, warm_tpu  # noqa: E402


GRID = [
    # round-4 probes, part 9 (small-instance rescue, round 2): fusion
    # and pop moved nothing (seeds 42/43 pinned at 16/20 across epd 1/4
    # and pop 32/64 — a genuine search plateau). Try move classes and
    # acceptance the current endgame lacks: 3-cycles (Move3 sweep
    # block), a hotter plateau walk in the post phase, deeper per-child
    # main-phase sweeps
    dict(p3=0.15),
    dict(post_sideways=0.5),
    dict(sweeps=8),
]


def main():
    name = sys.argv[1] if len(sys.argv) > 1 else "medium"
    budget = float(sys.argv[2]) if len(sys.argv) > 2 else 60.0
    seed = int(sys.argv[3]) if len(sys.argv) > 3 else 42
    from timetabling_ga_tpu.problem import dump_tim
    [(_name, problem)] = make_instances({name})
    with tempfile.NamedTemporaryFile("w", suffix=".tim",
                                     delete=False) as fh:
        fh.write(dump_tim(problem))
        path = fh.name
    for tune in GRID:
        warm_tpu(path, budget, seed, tune, problem.n_events)
        r = run_tpu(path, budget, seed, tune, problem.n_events)
        print(json.dumps({"instance": name, **r}), flush=True)
    os.unlink(path)


if __name__ == "__main__":
    main()
