"""One-off tuning probe: TPU-only quality-at-budget on one instance for a
grid of (pop, sweeps, swap_block, migration_period) configs, using the
race harness's exact warm/timed flow. Emits one JSON line per config.

Usage: python tools/tune_probe.py <instance> <budget> [seed]
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.quality_race import make_instances, run_tpu, warm_tpu  # noqa: E402


GRID = [
    # block_events > 1: E/B-depth sweep passes — many more passes per
    # second at 1/B acceptance density per pass
    dict(pop=1024, sweeps=4, init_sweeps=200, swap_block=8,
         block_events=8, migration_period=2, epochs_per_dispatch=1),
    dict(pop=512, sweeps=8, init_sweeps=400, swap_block=16,
         block_events=16, migration_period=2, epochs_per_dispatch=1),
    dict(pop=1024, sweeps=2, init_sweeps=100, swap_block=32,
         block_events=8, migration_period=2, epochs_per_dispatch=1),
    dict(pop=256, sweeps=16, init_sweeps=800, swap_block=16,
         block_events=32, migration_period=2, epochs_per_dispatch=1),
]


def main():
    name = sys.argv[1] if len(sys.argv) > 1 else "medium"
    budget = float(sys.argv[2]) if len(sys.argv) > 2 else 60.0
    seed = int(sys.argv[3]) if len(sys.argv) > 3 else 42
    from timetabling_ga_tpu.problem import dump_tim
    [(_name, problem)] = make_instances({name})
    with tempfile.NamedTemporaryFile("w", suffix=".tim",
                                     delete=False) as fh:
        fh.write(dump_tim(problem))
        path = fh.name
    for tune in GRID:
        warm_tpu(path, budget, seed, tune, problem.n_events)
        r = run_tpu(path, budget, seed, tune, problem.n_events)
        print(json.dumps({"instance": name, **r}), flush=True)
    os.unlink(path)


if __name__ == "__main__":
    main()
