"""One-off tuning probe: TPU-only quality-at-budget on one instance for a
grid of (pop, sweeps, swap_block, migration_period) configs, using the
race harness's exact warm/timed flow. Emits one JSON line per config.

Usage: python tools/tune_probe.py <instance> <budget> [seed]
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.quality_race import make_instances, run_tpu, warm_tpu  # noqa: E402


GRID = [
    # round-4 probes, part 6 (small instances, 30 s budget): the comp
    # winner was pop 16 + deep full-pivot post polish (comp01s 68,
    # comp05s 343 — the latter beating the round-3 CPU 351). Does the
    # same endgame recipe beat the shipped small defaults (pop 128,
    # 6 sweeps -> 17 vs CPU 14 in round 3)?
    dict(),   # shipped tuned defaults, as the baseline
    dict(pop=16, sweeps=2, hot_k=48, init_sweeps=200,
         migration_period=2, post_sweeps=16, post_swap_block=64,
         post_hot_k=0),
    dict(pop=32, post_sweeps=12, post_swap_block=64, post_hot_k=0),
]


def main():
    name = sys.argv[1] if len(sys.argv) > 1 else "medium"
    budget = float(sys.argv[2]) if len(sys.argv) > 2 else 60.0
    seed = int(sys.argv[3]) if len(sys.argv) > 3 else 42
    from timetabling_ga_tpu.problem import dump_tim
    [(_name, problem)] = make_instances({name})
    with tempfile.NamedTemporaryFile("w", suffix=".tim",
                                     delete=False) as fh:
        fh.write(dump_tim(problem))
        path = fh.name
    for tune in GRID:
        warm_tpu(path, budget, seed, tune, problem.n_events)
        r = run_tpu(path, budget, seed, tune, problem.n_events)
        print(json.dumps({"instance": name, **r}), flush=True)
    os.unlink(path)


if __name__ == "__main__":
    main()
