"""Head-to-head solution-quality race: TPU engine vs the
reference-faithful CPU baseline at fixed wall clock (VERDICT round-1
item 1 — the capability claim).

Baseline: `tt_cpu --algo reference` (native/timetabling_native.cpp) —
steady-state pop-10 GA with the reference's exhaustive first-improvement
sweep LS and exact per-slot maximum matching, at full host cores.

Contender: the TPU engine (runtime/engine.py) with the batched sweep LS
run to convergence per child and an LS-polished initial population.

Both sides get the same instances (ITC-2002-scale synthetics, regular
AND room-tight) and the same wall-clock budget; jit compilation is
warmed out of the budget first (the reference binary is also "compiled"
ahead of time), which the engine's module-level compiled-runner cache
makes real — the timed run reuses the warm run's programs. Output: one
result JSON per (instance, seed) on stdout plus a markdown summary
table on stderr, for BASELINE.md.

Asymmetric budgets (VERDICT round-4 next #1 — the honest 32-core
extrapolation): `--cpu-budget-factor N` gives the CPU side N x the TPU
wall-clock budget, measured in PROCESS CPU TIME (`tt_cpu --clock cpu`)
so the number is immune to machine contention and equals what N OpenMP
threads splitting the generation budget (ga.cpp:510) would burn in 1 x
wall. `--no-tpu` runs only the CPU legs (so the long legs can run in
the background), `--no-cpu` only the TPU legs; rows from separate
invocations carry the same keys and merge by (instance, seed).

Island legs (VERDICT round-4 next #2): `--cpu-islands N` runs the CPU
side as N islands with ring migration (tt_cpu --islands); `--tpu-islands
N` requests N islands on the TPU side — N may exceed the device count
(each device then carries N/devices vmapped local islands; see
parallel/islands.py local_islands). `--nsga2` switches the TPU side to
the NSGA-II replacement stage.

Quality-explained rows (ISSUE 9): `--quality` runs every TPU leg with
the search-quality observatory on and attaches a "quality" dict to its
row — diversity trend (Hamming first -> final), crossover/mutation win
rates, sweep Move1/2/3 accepts, migration gain, and stall/kick counts —
so a race result explains WHY one strategy won, not just that it did.
Opt-IN deliberately: races are BUDGET-bound (generations=1e9 under -t),
so the observatory's per-dispatch host cost buys fewer generations per
budget — the telemetry is trajectory-identical per generation, but a
quality row is not wall-clock-comparable against the pre-PR-9 history
rows; flip the flag on both sides of a comparison.

Usage:
  python tools/quality_race.py [--budget S] [--quick] [--seeds a,b,c]
      [--pop N] [--sweeps N] [--init-sweeps N] [--swap-block N]
      [--instances small,small-tight,...] [--no-cpu] [--no-tpu]
      [--cpu-budget-factor N] [--cpu-islands N] [--tpu-islands N]
      [--nsga2] [--quality]
"""

from __future__ import annotations

import io
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
TT_CPU = os.path.join(REPO, "native", "tt_cpu")

SPECS = [
    # name, generator-name, E, R, S, attend_prob
    ("small", "random", 100, 5, 80, 0.05),
    ("small-tight", "tight", 100, 5, 80, 0.05),
    ("medium", "random", 400, 10, 200, 0.02),
    ("medium-tight", "tight", 400, 10, 200, 0.02),
]

# committed ITC-2002-style fixtures (fixtures/README.md): these have a
# planted perfect solution, so best-at-budget is comparable to the
# published competition evaluation (lower = closer to the known optimum 0)
FIXTURE_SPECS = ["comp01s", "comp05s"]


def make_instances(names):
    from timetabling_ga_tpu.problem import (
        load_tim_file, random_instance, room_tight_instance)
    gens = {"random": random_instance, "tight": room_tight_instance}
    out = []
    for name, gen, E, R, S, ap in SPECS:
        if names and name not in names:
            continue
        out.append((name, gens[gen](101, n_events=E, n_rooms=R,
                                    n_features=5, n_students=S,
                                    attend_prob=ap)))
    for name in FIXTURE_SPECS:
        if names and name not in names:
            continue
        out.append((name, load_tim_file(
            os.path.join(REPO, "fixtures", f"{name}.tim"))))
    return out


def _first_feasible_time(lines):
    for x in lines:
        if "logEntry" in x and x["logEntry"]["best"] < 1_000_000:
            return x["logEntry"]["time"]
    return None


def run_cpu_baseline(tim_path: str, budget: float, seed: int,
                     factor: float = 1.0, islands: int = 1,
                     clock: str = None) -> dict:
    if clock is None:
        clock = "cpu" if factor != 1.0 else "wall"
    if clock not in ("wall", "cpu"):
        raise SystemExit(f"unknown --cpu-clock: {clock} (wall|cpu)")
    # wall mode: full host cores (the symmetric-race baseline). cpu mode:
    # ONE thread — process CPU time is summed across threads, so N
    # threads would burn the budget N x faster in wall terms and the
    # recorded factor would overstate the handicap; the one-thread
    # protocol keeps "factor N" == "N threads at 1x wall" exactly.
    threads = 1 if clock == "cpu" else (os.cpu_count() or 1)
    cpu_budget = budget * factor
    cmd = [TT_CPU, "-i", tim_path, "-s", str(seed), "-c", str(threads),
           "-t", str(cpu_budget), "--algo", "reference",
           "--generations", "1000000"]
    if clock == "cpu":
        # budget measured in process CPU time: immune to contention when
        # baseline legs run in the background (see module doc). NOTE the
        # binary's logEntry timestamps (time_to_feasible_s) are then CPU
        # seconds too — the "clock" field in the result records which.
        cmd += ["--clock", "cpu"]
    if islands > 1:
        cmd += ["--islands", str(islands)]
    t0 = time.perf_counter()
    out = subprocess.run(
        cmd, capture_output=True, text=True,
        timeout=cpu_budget * 4 + 300, check=True)
    dt = time.perf_counter() - t0
    lines = [json.loads(x) for x in out.stdout.splitlines()]
    run_entries = [x["runEntry"] for x in lines if "runEntry" in x]
    return {"best": run_entries[-1]["totalBest"],
            "feasible": run_entries[-1]["feasible"],
            "time_to_feasible_s": _first_feasible_time(lines),
            "wall_s": round(dt, 1), "threads": threads,
            "budget_s": cpu_budget, "islands": islands,
            "clock": clock}


_TUNE_FIELDS = {"pop": "pop_size", "sweeps": "ls_sweeps",
                "p3": "p3",
                "init_sweeps": "init_sweeps",
                "swap_block": "ls_swap_block",
                "migration_period": "migration_period",
                "block_events": "ls_block_events",
                "sideways": "ls_sideways",
                "hot_k": "ls_hot_k",
                "post_sweeps": "post_ls_sweeps",
                "post_swap_block": "post_swap_block",
                "post_hot_k": "post_hot_k",
                "post_sideways": "post_sideways",
                "post_lahc": "post_lahc",
                "post_lahc_k": "post_lahc_k",
                "post_pop": "post_pop_size",
                "epochs_per_dispatch": "epochs_per_dispatch",
                "tpu_islands": "islands",
                "kick_stall": "kick_stall",
                "nsga2": "nsga2"}


def tpu_config(tim_path: str, budget: float, seed: int, tune: dict,
               n_events: int, quality: bool = False):
    """Explicit --pop/--sweeps/... flags win; anything left unset takes
    the size-tuned solver defaults (RunConfig.apply_tuned_defaults, the
    production rule — so the race measures the SHIPPED configuration
    unless the operator overrides it). `quality` switches on the
    search-quality observatory (+ --obs for the qualityEntry stream):
    trajectory-identical per generation (tests/test_quality.py pins
    it), but the per-dispatch host cost means a BUDGET-bound leg
    completes fewer generations — see the module docstring on
    comparability."""
    from timetabling_ga_tpu.runtime.config import RunConfig
    cfg = RunConfig(input=tim_path, seed=seed, islands=1,
                    generations=10 ** 9, time_limit=budget,
                    quality=quality, obs=quality)
    # tuned defaults FIRST, explicit flags after — the other order would
    # drop an explicit flag whose value coincides with the dataclass
    # default (apply_tuned_defaults cannot tell those apart)
    cfg.apply_tuned_defaults(n_events)
    for k, field in _TUNE_FIELDS.items():
        if tune.get(k) is not None:
            setattr(cfg, field, tune[k])
    return cfg


def warm_tpu(tim_path: str, budget: float, seed: int, tune: dict,
             n_events: int, quality: bool = False):
    """Compile + measure outside the budget via engine.precompile: every
    program a timed run can dispatch (init, epoch runner, dynamic tail
    runner) lands in the module-level caches, and the seconds-per-
    generation estimate is seeded from a clean post-compile dispatch."""
    from timetabling_ga_tpu.runtime import engine
    engine.precompile(tpu_config(tim_path, budget, seed, tune, n_events,
                                 quality))


def _quality_summary(lines) -> dict:
    """Per-strategy quality telemetry from the run's qualityEntry /
    faultEntry stream — the WHY behind a race row's final penalty
    (ROADMAP item 5): did diversity collapse, which operators earned
    their cycles, did migration move anything, how long was the run
    stalled."""
    from timetabling_ga_tpu.obs.quality import (entry_total,
                                                entry_win_rate)
    qes = [x["qualityEntry"] for x in lines if "qualityEntry" in x]
    stalls = [x["faultEntry"] for x in lines
              if x.get("faultEntry", {}).get("site") == "quality"]
    if not qes:
        return {}
    first, last = qes[0], qes[-1]
    return {
        "hamming_first": first.get("quality.diversity.hamming"),
        "hamming_final": last.get("quality.diversity.hamming"),
        "crossover_win_rate": entry_win_rate(
            qes, "quality.ops.crossover_wins",
            "quality.ops.crossover_attempts"),
        "mutation_win_rate": entry_win_rate(
            qes, "quality.ops.mutation_wins",
            "quality.ops.mutation_attempts"),
        "sweep_accepts": [entry_total(qes, "quality.ops.move1_accepts"),
                          entry_total(qes, "quality.ops.move2_accepts"),
                          entry_total(qes, "quality.ops.move3_accepts")],
        "migration_gain": entry_total(qes, "quality.migration.gain"),
        "stall_events": sum(1 for f in stalls
                            if f.get("action") == "stall"),
        "kick_events": sum(1 for f in stalls
                           if f.get("action") == "kick"),
    }


def run_tpu(tim_path: str, budget: float, seed: int, tune: dict,
            n_events: int, quality: bool = False) -> dict:
    from timetabling_ga_tpu.runtime import engine
    cfg = tpu_config(tim_path, budget, seed, tune, n_events, quality)
    buf = io.StringIO()
    t0 = time.perf_counter()
    best = engine.run(cfg, out=buf)
    dt = time.perf_counter() - t0
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    used = {k: getattr(cfg, field) for k, field in _TUNE_FIELDS.items()}
    row = {"best": best, "feasible": best < 1_000_000,
           "time_to_feasible_s": _first_feasible_time(lines),
           "wall_s": round(dt, 1), **used}
    if quality:
        row["quality"] = _quality_summary(lines)
    return row


def _tpu_retry(fn, *args):
    """Run a TPU-side race step through the shared sick-window retry
    policy (timetabling_ga_tpu.runtime.retry)."""
    from timetabling_ga_tpu.runtime.retry import retry_unavailable
    return retry_unavailable(fn, *args, attempts=3, wait_s=90.0)


def main():
    argv = sys.argv[1:]

    def opt(name, default, typ=float):
        if name in argv:
            return typ(argv[argv.index(name) + 1])
        return default

    budget = opt("--budget", 60.0)
    seeds = [int(s) for s in str(opt("--seeds", "42", str)).split(",")]
    names = None
    known = {s[0] for s in SPECS} | set(FIXTURE_SPECS)
    if "--instances" in argv:
        names = set(opt("--instances", "", str).split(","))
        unknown = names - known
        if unknown:
            sys.exit(f"unknown instance(s): {sorted(unknown)}; "
                     f"choose from {sorted(known)}")
    elif "--quick" in argv:
        names = {"small", "small-tight"}
    tune = {
        "pop": opt("--pop", None, int),
        "sweeps": opt("--sweeps", None, int),
        "init_sweeps": opt("--init-sweeps", None, int),
        "swap_block": opt("--swap-block", None, int),
        "migration_period": opt("--migration-period", None, int),
        "block_events": opt("--block-events", None, int),
        "sideways": opt("--sideways", None, float),
        "hot_k": opt("--hot-k", None, int),
        "post_sweeps": opt("--post-sweeps", None, int),
        "post_swap_block": opt("--post-swap-block", None, int),
        "post_hot_k": opt("--post-hot-k", None, int),
        "post_sideways": opt("--post-sideways", None, float),
        "post_lahc": opt("--post-lahc", None, int),
        "post_lahc_k": opt("--post-lahc-k", None, int),
        "post_pop": opt("--post-pop", None, int),
        "epochs_per_dispatch": opt("--epochs-per-dispatch", None, int),
        "tpu_islands": opt("--tpu-islands", None, int),
        "kick_stall": opt("--kick-stall", None, int),
        "nsga2": True if "--nsga2" in argv else None,
    }
    do_cpu = "--no-cpu" not in argv
    do_tpu = "--no-tpu" not in argv
    # per-strategy quality telemetry, opt-IN (module docstring: the
    # observatory's host cost buys fewer generations per wall-clock
    # budget, so quality rows are not comparable to non-quality ones)
    quality = "--quality" in argv
    cpu_factor = opt("--cpu-budget-factor", 1.0)
    cpu_islands = opt("--cpu-islands", 1, int)
    cpu_clock = opt("--cpu-clock", None, str)

    from timetabling_ga_tpu.problem import dump_tim
    rows = []
    for name, problem in make_instances(names):
        with tempfile.NamedTemporaryFile(
                "w", suffix=".tim", delete=False) as fh:
            fh.write(dump_tim(problem))
            tim_path = fh.name
        if do_tpu:
            _tpu_retry(warm_tpu, tim_path, budget, seeds[0], tune,
                       problem.n_events, quality)
        for seed in seeds:
            cpu = (run_cpu_baseline(tim_path, budget, seed,
                                    factor=cpu_factor,
                                    islands=cpu_islands,
                                    clock=cpu_clock)
                   if do_cpu else None)
            tpu = (_tpu_retry(run_tpu, tim_path, budget, seed, tune,
                              problem.n_events, quality)
                   if do_tpu else None)
            row = {"instance": name, "budget_s": budget, "seed": seed,
                   "cpu_budget_factor": cpu_factor,
                   "cpu": cpu, "tpu": tpu}
            if cpu is not None and tpu is not None:
                row["tpu_wins"] = tpu["best"] <= cpu["best"]
            rows.append(row)
            print(json.dumps(row), flush=True)
        os.unlink(tim_path)

    if do_cpu and do_tpu:
        print("\n| instance | seed | budget | CPU ref best | TPU best | "
              "CPU t-to-feas | TPU t-to-feas | winner |", file=sys.stderr)
        print("|---|---|---|---|---|---|---|---|", file=sys.stderr)
        for r in rows:
            print(f"| {r['instance']} | {r['seed']} | "
                  f"{r['budget_s']:.0f}s | "
                  f"{r['cpu']['best']} | {r['tpu']['best']} | "
                  f"{r['cpu']['time_to_feasible_s']} | "
                  f"{r['tpu']['time_to_feasible_s']} | "
                  f"{'TPU' if r['tpu_wins'] else 'CPU'} |",
                  file=sys.stderr)
        wins = sum(r["tpu_wins"] for r in rows)
        print(f"\nTPU wins {wins}/{len(rows)}", file=sys.stderr)


if __name__ == "__main__":
    main()
