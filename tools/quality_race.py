"""Head-to-head solution-quality race: TPU engine vs the
reference-faithful CPU baseline at fixed wall clock (VERDICT round-1
item 1 — the capability claim).

Baseline: `tt_cpu --algo reference` (native/timetabling_native.cpp) —
steady-state pop-10 GA with the reference's exhaustive first-improvement
sweep LS and exact per-slot maximum matching, at full host cores.

Contender: the TPU engine (runtime/engine.py) with the batched sweep LS.

Both sides get the same instances (ITC-2002-scale synthetics, regular
AND room-tight) and the same wall-clock budget; jit compilation is
warmed out of the budget first (the reference binary is also "compiled"
ahead of time). Output: one result JSON per race on stdout plus a
markdown table on stderr, for BASELINE.md.

Usage: python tools/quality_race.py [--budget SECONDS] [--quick]
"""

from __future__ import annotations

import io
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
TT_CPU = os.path.join(REPO, "native", "tt_cpu")


def make_instances(quick: bool):
    from timetabling_ga_tpu.problem import (
        random_instance, room_tight_instance)
    specs = [
        # name, generator, E, R, S, attend_prob
        ("small", random_instance, 100, 5, 80, 0.05),
        ("small-tight", room_tight_instance, 100, 5, 80, 0.05),
        ("medium", random_instance, 400, 10, 200, 0.02),
        ("medium-tight", room_tight_instance, 400, 10, 200, 0.02),
    ]
    if quick:
        specs = specs[:2]
    out = []
    for name, gen, E, R, S, ap in specs:
        out.append((name, gen(101, n_events=E, n_rooms=R, n_features=5,
                              n_students=S, attend_prob=ap)))
    return out


def run_cpu_baseline(tim_path: str, budget: float, seed: int) -> dict:
    threads = os.cpu_count() or 1
    t0 = time.perf_counter()
    out = subprocess.run(
        [TT_CPU, "-i", tim_path, "-s", str(seed), "-c", str(threads),
         "-t", str(budget), "--algo", "reference",
         "--generations", "1000000"],
        capture_output=True, text=True, timeout=budget * 3 + 120,
        check=True)
    dt = time.perf_counter() - t0
    lines = [json.loads(x) for x in out.stdout.splitlines()]
    run_entries = [x["runEntry"] for x in lines if "runEntry" in x]
    feas_time = None
    for x in lines:
        if "logEntry" in x and x["logEntry"]["best"] < 1_000_000:
            feas_time = x["logEntry"]["time"]
            break
    return {"best": run_entries[-1]["totalBest"],
            "feasible": run_entries[-1]["feasible"],
            "time_to_feasible_s": feas_time,
            "wall_s": round(dt, 1), "threads": threads}


def run_tpu(problem, tim_path: str, budget: float, seed: int,
            pop: int, ls_mode: str) -> dict:
    import jax
    from timetabling_ga_tpu.runtime.config import RunConfig
    from timetabling_ga_tpu.runtime import engine

    cfg = RunConfig(input=tim_path, seed=seed, pop_size=pop, islands=1,
                    generations=10 ** 9, migration_period=10,
                    time_limit=budget, ls_mode=ls_mode, ls_sweeps=1,
                    max_steps=200, epochs_per_dispatch=1)
    # warm the jit cache outside the budget (one epoch on same shapes)
    warm_cfg = RunConfig(**{**cfg.__dict__, "generations": 10,
                            "time_limit": 10 ** 6})
    engine.run(warm_cfg, out=io.StringIO())

    buf = io.StringIO()
    t0 = time.perf_counter()
    best = engine.run(cfg, out=buf)
    dt = time.perf_counter() - t0
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    feas_time = None
    for x in lines:
        if "logEntry" in x and x["logEntry"]["best"] < 1_000_000:
            feas_time = x["logEntry"]["time"]
            break
    return {"best": best, "feasible": best < 1_000_000,
            "time_to_feasible_s": feas_time, "wall_s": round(dt, 1),
            "pop": pop, "ls_mode": ls_mode}


def main():
    from timetabling_ga_tpu.problem import dump_tim
    budget = 60.0
    quick = "--quick" in sys.argv
    if "--budget" in sys.argv:
        budget = float(sys.argv[sys.argv.index("--budget") + 1])

    rows = []
    for name, problem in make_instances(quick):
        with tempfile.NamedTemporaryFile(
                "w", suffix=".tim", delete=False) as fh:
            fh.write(dump_tim(problem))
            tim_path = fh.name
        cpu = run_cpu_baseline(tim_path, budget, seed=42)
        tpu = run_tpu(problem, tim_path, budget, seed=42,
                      pop=2048, ls_mode="sweep")
        row = {"instance": name, "budget_s": budget, "cpu": cpu,
               "tpu": tpu,
               "tpu_wins": tpu["best"] <= cpu["best"]}
        rows.append(row)
        print(json.dumps(row))
        os.unlink(tim_path)

    print("\n| instance | budget | CPU ref best | TPU best | "
          "CPU t-to-feas | TPU t-to-feas | winner |", file=sys.stderr)
    print("|---|---|---|---|---|---|---|", file=sys.stderr)
    for r in rows:
        print(f"| {r['instance']} | {r['budget_s']:.0f}s | "
              f"{r['cpu']['best']} | {r['tpu']['best']} | "
              f"{r['cpu']['time_to_feasible_s']} | "
              f"{r['tpu']['time_to_feasible_s']} | "
              f"{'TPU' if r['tpu_wins'] else 'CPU'} |", file=sys.stderr)


if __name__ == "__main__":
    main()
