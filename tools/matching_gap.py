"""Measure the matcher-attributable hcv gap on the DEVICE path during a
production run (VERDICT round-4 next #3).

The reference's primary room assigner is an exact per-slot maximum
matching (Solution::maxMatching, Solution.cpp:836-891); the TPU
production path uses the greedy scan (ops/rooms.py assign_rooms) and the
hcv penalty absorbs any imperfection. This tool puts a NUMBER on that
absorption: it runs the shipped engine configuration on the room-tight
fixtures, snapshots the final population via the checkpoint path, and
for every individual compares

  greedy   = assignment_room_hcv(slots, rooms)      # what the run has
  exact_lb = room_hcv_lower_bound(slots)            # Hopcroft-Karp bound
  augment  = assignment_room_hcv(slots, augment_rooms(slots, rooms))

`greedy - exact_lb` is the hcv the matcher leaves on the table; if the
bounded augmenting matcher (already built, ops/rooms.py:augment_rooms)
closes it, wiring it into the breeding rematch is worth a re-race.

Usage: python tools/matching_gap.py [--budget S] [--instances a,b]
       [--seeds a,b,c]
Output: one JSON line per (instance, seed) + a summary table on stderr.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def measure(problem, state_slots, state_rooms) -> dict:
    """Population-wide matcher slack: greedy-vs-exact and augment-vs-
    exact, plus the best row's numbers (row 0 is the reported one)."""
    import numpy as np

    from timetabling_ga_tpu.oracle.matching import (
        assignment_room_hcv, room_hcv_lower_bound)
    from timetabling_ga_tpu.ops.rooms import augment_rooms

    import jax

    pa = problem.device_arrays()
    aug = np.asarray(jax.jit(jax.vmap(
        lambda s, r: augment_rooms(pa, s, r)))(state_slots, state_rooms))

    rows = []
    for i in range(state_slots.shape[0]):
        s = np.asarray(state_slots[i])
        lb = room_hcv_lower_bound(problem, s)
        g = assignment_room_hcv(problem, s, np.asarray(state_rooms[i]))
        a = assignment_room_hcv(problem, s, aug[i])
        rows.append((g, a, lb))
    g = np.array([r[0] for r in rows])
    a = np.array([r[1] for r in rows])
    lb = np.array([r[2] for r in rows])
    return {
        "pop": len(rows),
        "best_row": {"greedy": int(g[0]), "augment": int(a[0]),
                     "exact_lb": int(lb[0]),
                     "slack_greedy": int(g[0] - lb[0]),
                     "slack_augment": int(a[0] - lb[0])},
        "mean_slack_greedy": round(float((g - lb).mean()), 3),
        "max_slack_greedy": int((g - lb).max()),
        "mean_slack_augment": round(float((a - lb).mean()), 3),
        "max_slack_augment": int((a - lb).max()),
        "frac_rows_with_greedy_slack": round(float((g > lb).mean()), 3),
    }


def run_one(name: str, problem, budget: float, seed: int) -> dict:
    """Production run (tuned defaults, like the race) with a checkpoint;
    measure on the checkpointed final population."""
    import numpy as np

    from timetabling_ga_tpu.runtime import checkpoint as ckpt
    from timetabling_ga_tpu.runtime import engine
    from timetabling_ga_tpu.problem import dump_tim
    from timetabling_ga_tpu.runtime.config import RunConfig

    with tempfile.NamedTemporaryFile("w", suffix=".tim",
                                     delete=False) as fh:
        fh.write(dump_tim(problem))
        tim_path = fh.name
    ck = tempfile.mktemp(suffix=".npz")
    try:
        cfg = RunConfig(input=tim_path, seed=seed, islands=1,
                        generations=10 ** 9, time_limit=budget,
                        checkpoint=ck, checkpoint_every=1)
        cfg.apply_tuned_defaults(problem.n_events)
        engine.precompile(cfg)
        import io
        t0 = time.perf_counter()
        best = engine.run(cfg, out=io.StringIO())
        wall = time.perf_counter() - t0
        state, _key, gens, _bs, _seed = ckpt.load(
            ck, ckpt.config_fingerprint(
                problem, engine.build_ga_config(cfg), 1))
        m = measure(problem, np.asarray(state.slots),
                    np.asarray(state.rooms))
        return {"instance": name, "seed": seed, "budget_s": budget,
                "best": int(best), "gens_at_snapshot": gens,
                "wall_s": round(wall, 1), **m}
    finally:
        os.unlink(tim_path)
        if os.path.exists(ck):
            os.unlink(ck)


def main():
    argv = sys.argv[1:]

    def opt(name, default, typ=float):
        if name in argv:
            return typ(argv[argv.index(name) + 1])
        return default

    budget = opt("--budget", 30.0)
    seeds = [int(s) for s in str(opt("--seeds", "42", str)).split(",")]
    names = str(opt("--instances", "small-tight,comp05s", str)).split(",")

    from tools.quality_race import make_instances
    from timetabling_ga_tpu.runtime.retry import retry_unavailable

    out_rows = []
    for name, problem in make_instances(set(names)):
        for seed in seeds:
            row = retry_unavailable(run_one, name, problem, budget, seed,
                                    attempts=3, wait_s=90.0)
            out_rows.append(row)
            print(json.dumps(row), flush=True)

    print("\n| instance | seed | best | best-row greedy/aug/exact | "
          "pop mean slack greedy/aug |", file=sys.stderr)
    print("|---|---|---|---|---|", file=sys.stderr)
    for r in out_rows:
        b = r["best_row"]
        print(f"| {r['instance']} | {r['seed']} | {r['best']} | "
              f"{b['greedy']}/{b['augment']}/{b['exact_lb']} | "
              f"{r['mean_slack_greedy']}/{r['mean_slack_augment']} |",
              file=sys.stderr)


if __name__ == "__main__":
    main()
