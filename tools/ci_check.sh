#!/usr/bin/env bash
# Pre-PR gate: run this before every commit that touches the package.
#
#   tools/ci_check.sh                # full gate: lint + compile + tier-1
#   tools/ci_check.sh --fast         # lint + compile + sub-minute tests
#   tools/ci_check.sh --analyze-only # the strict whole-program analyzer
#                                    # pass alone (editor/pre-commit hook
#                                    # speed: seconds)
#   tools/ci_check.sh --perf FILE    # perf-regression gate alone: a
#                                    # fresh bench JSON (or driver
#                                    # capture) vs the newest committed
#                                    # BENCH_r*.json (tools/perf_gate.py)
#
# Steps (each failure is fatal):
#   1. tt-analyze --strict --warn-unused-ignores over timetabling_ga_tpu/
#      — the JAX-aware static rules, 26 of them including the
#      whole-program device-taint/donation/fence/residency pass
#      (TT303/TT304/TT305/TT306), the tt-accord recovery-path
#      collective ban (TT307) and the tt-prof phase-registry check
#      (TT310), plus stale-suppression detection
#      (TT901; README "Static analysis & sanitizers")
#   2. python -m compileall — syntax across every tree we ship
#   3. the tier-1 pytest command from ROADMAP.md
set -u -o pipefail

cd "$(dirname "$0")/.."

fail=0
step() {
    echo "== ci_check: $1" >&2
}

if [ "${1:-}" = "--perf" ]; then
    # standalone mode: no analyzer/test run — compare a fresh bench
    # result against the committed perf history and exit nonzero on a
    # regression beyond tolerance (tools/perf_gate.py)
    if [ -z "${2:-}" ]; then
        echo "usage: ci_check.sh --perf <fresh-bench.json>" >&2
        exit 2
    fi
    step "perf gate (tools/perf_gate.py vs newest BENCH_r*.json)"
    python tools/perf_gate.py "$2" || fail=1
    [ "$fail" -eq 0 ] && step "OK (perf gate)"
    [ "$fail" -ne 0 ] && step "FAILED"
    exit $fail
fi

step "tt-analyze --strict --warn-unused-ignores timetabling_ga_tpu/"
JAX_PLATFORMS=cpu python -m timetabling_ga_tpu.analysis --strict \
    --warn-unused-ignores timetabling_ga_tpu/ || fail=1

if [ "${1:-}" = "--analyze-only" ]; then
    [ "$fail" -eq 0 ] && step "OK (analyze-only: compile + test tiers skipped)"
    [ "$fail" -ne 0 ] && step "FAILED"
    exit $fail
fi

step "compileall"
python -m compileall -q timetabling_ga_tpu tests tools bench.py || fail=1

if [ "${1:-}" = "--fast" ]; then
    # fast mode still exercises the serve + obs subsystems end-to-end:
    # their test modules are minutes not tens of minutes, and together
    # they span enough layers (bucketing neutrality, compile-once,
    # scheduler fairness, span/metrics record fencing, trace-mode
    # stream equivalence, the pull front's /metrics//healthz//readyz
    # endpoints + scrape/obs_listen fault isolation, flow-event export
    # and backpressure shedding) that a lint-only gate would miss real
    # breakage
    step "serve tests (tests/test_serve.py)"
    timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
        tests/test_serve.py -q -p no:cacheprovider || fail=1
    step "obs tests (tests/test_obs.py)"
    timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
        tests/test_obs.py -q -p no:cacheprovider || fail=1
    step "cost observatory tests (tests/test_cost.py)"
    timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
        tests/test_cost.py -q -p no:cacheprovider || fail=1
    step "search-quality observatory tests (tests/test_quality.py)"
    timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
        tests/test_quality.py -q -p no:cacheprovider || fail=1
    step "fleet tests (tests/test_fleet.py)"
    timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
        tests/test_fleet.py -q -p no:cacheprovider || fail=1
    step "fleet observatory tests (tests/test_fleet_obs.py)"
    timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
        tests/test_fleet_obs.py -q -p no:cacheprovider || fail=1
    step "fleet resume tests (tests/test_resume.py)"
    timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
        tests/test_resume.py -q -p no:cacheprovider || fail=1
    # the flight e2e acceptance is tier-marked slow (a full
    # gateway+2-replica kill scenario); fast mode runs the
    # unit/endpoint/isolation tier
    step "flight recorder tests (tests/test_flight.py)"
    timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
        tests/test_flight.py -q -p no:cacheprovider -m 'not slow' \
        || fail=1
    # likewise the usage acceptance (kill-mid-job tenant-total match);
    # fast mode runs the conservation/identity/continuity/fleet tier
    step "usage metering tests (tests/test_usage.py)"
    timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
        tests/test_usage.py -q -p no:cacheprovider -m 'not slow' \
        || fail=1
    # and the autoscaler acceptance (burst scale-up + preempt
    # scale-down e2e) is slow-tiered; fast mode runs the policy/
    # warmth-guard/cooldown/isolation tier
    step "autoscaler tests (tests/test_scale.py)"
    timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
        tests/test_scale.py -q -p no:cacheprovider -m 'not slow' \
        || fail=1
    # the tt-accord acceptance (2-process kill-mid-run) is slow-tiered;
    # fast mode runs the loopback fault matrix — every agreement path,
    # heartbeat expiry and verdict merge on single-process CPU
    step "accord channel tests (tests/test_accord.py)"
    timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
        tests/test_accord.py -q -p no:cacheprovider -m 'not slow' \
        || fail=1
    # tt-edit: anchored-objective neutrality/bit-exactness, the
    # transplant warm/demote matrix, and the w_anchor=0 stream-
    # identity pin — the incremental re-solve acceptance tier
    step "incremental re-solve tests (tests/test_edit.py)"
    timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
        tests/test_edit.py -q -p no:cacheprovider -m 'not slow' \
        || fail=1
    # tt-prof: parser units, scope-identity A/B, attribution honesty,
    # hotspot CLI and perf-gate units; the heavy capture e2e is
    # slow-tiered
    step "phase profiler tests (tests/test_prof.py)"
    timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest \
        tests/test_prof.py -q -p no:cacheprovider -m 'not slow' \
        || fail=1
    [ "$fail" -eq 0 ] && step "OK (fast mode: full test tier skipped)"
    exit $fail
fi

step "tier-1 pytest (ROADMAP.md)"
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
[ "$rc" -ne 0 ] && fail=1

if [ "$fail" -eq 0 ]; then
    step "OK"
else
    step "FAILED"
fi
exit $fail
