"""LAHC endgame tuning grid: quality-at-budget on one instance for a
grid of (post_lahc history length, walker count) configs vs the shipped
GA endgame, via the race harness's exact warm/timed flow.

Usage: python tools/lahc_probe.py <instance> <budget> [seed [grid]]
  grid = comma-separated entries "Lh:walkers[:K]" (0:0 = GA endgame
  baseline; walkers 0 = keep the tuned post_pop_size; K = candidates
  per walker per step, default the shipped post_lahc_k)
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.quality_race import make_instances, run_tpu, warm_tpu  # noqa: E402


def main():
    name = sys.argv[1] if len(sys.argv) > 1 else "comp01s"
    budget = float(sys.argv[2]) if len(sys.argv) > 2 else 60.0
    seed = int(sys.argv[3]) if len(sys.argv) > 3 else 42
    grid_s = (sys.argv[4] if len(sys.argv) > 4
              else "0:0,5000:0,5000:16,20000:16,1000:16")
    grid = []
    for ent in grid_s.split(","):
        parts = ent.split(":")
        lh, w = int(parts[0]), int(parts[1])
        tune = {}
        if lh > 0:
            tune["post_lahc"] = lh
        if w > 0:
            tune["post_pop"] = w
        if len(parts) > 2:
            tune["post_lahc_k"] = int(parts[2])
        grid.append(tune)

    from timetabling_ga_tpu.problem import dump_tim
    [(_name, problem)] = make_instances({name})
    with tempfile.NamedTemporaryFile("w", suffix=".tim",
                                     delete=False) as fh:
        fh.write(dump_tim(problem))
        path = fh.name
    for tune in grid:
        warm_tpu(path, budget, seed, tune, problem.n_events)
        r = run_tpu(path, budget, seed, tune, problem.n_events)
        r["post_lahc"] = tune.get("post_lahc", 0)
        r["post_pop"] = tune.get("post_pop")
        r["post_lahc_k"] = tune.get("post_lahc_k")
        print(json.dumps({"instance": name, "seed": seed, **r}),
              flush=True)
    os.unlink(path)


if __name__ == "__main__":
    main()
