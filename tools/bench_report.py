"""Perf-history trajectory report: BENCH_r*.json / MULTICHIP_r*.json
-> one table.

Each PR round leaves a `BENCH_r0N.json` (the driver's capture of
`python bench.py`: {"n", "cmd", "rc", "tail", "parsed"}) and a
`MULTICHIP_r0N.json` in the repo root. The perf history is currently
unreadable without hand-diffing five of them — worse, the captures are
imperfect: `parsed` is often null and `tail` keeps only the LAST ~2000
characters of stdout, which can clip the head off the final JSON line.
This tool salvages what each round actually recorded:

  1. `parsed` when the driver managed to parse the bench JSON;
  2. else the largest JSON object decodable from `tail` (scanning
     forward from each '{' — survives a head-clipped tail whose final
     legs are intact);
  3. else regex extraction of the known metric keys from the raw text
     (`"gens_per_sec": 12.3` fragments survive any truncation).

Output: a markdown trajectory table per metric family (throughput,
dispatch pipeline host gap, serve soak, compile-hit rate), one row per
round, plus the multichip dry-run status — the at-a-glance answer to
"did round N regress round N-1".

    python tools/bench_report.py               # tables on stdout
    python tools/bench_report.py --json        # raw extracted dicts
    python tools/bench_report.py --metrics snap.txt
        render a saved /metrics exposition snapshot (e.g. `curl
        gateway:8070/metrics > snap.txt`) as a table, via the shared
        OpenMetrics parser (obs/scrape.py) — the fleet dashboard with
        no Prometheus installed: gauges/counters one per line,
        histograms as count/sum + their exemplars

Stdlib-only and device-free: reading the history must work anywhere
the repo is checked out (the --metrics mode imports only
timetabling_ga_tpu.obs.scrape, itself stdlib-only).
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (column header, leg, key). leg=None: the key is globally unique in
# the bench JSON, searched flat over whatever text survived the tail
# truncation. leg="<name>": the key appears under SEVERAL legs
# (gen_per_sec is emitted by both generation_scan and
# generation_parallel), so the lookup is scoped to that leg's object —
# a flat first-match would source the column from whichever leg
# survived a given round's truncation, silently comparing different
# configurations across rounds.
_METRICS = [
    ("gens/s scan", "generation_scan", "gen_per_sec"),
    ("gens/s parallel", "generation_parallel", "gen_per_sec"),
    ("ms/gen sweep128", "generation_sweep_128", "ms_per_gen"),
    ("host gap ms/gen serial", None, "host_gap_ms_per_gen_serial"),
    ("host gap ms/gen piped", None, "host_gap_ms_per_gen_pipelined"),
    ("loop speedup", None, "loop_speedup"),
    ("soak jobs/min", None, "jobs_per_min"),
    ("soak p50 s", None, "p50_latency_s"),
    ("soak p99 s", None, "p99_latency_s"),
    ("compile-hit rate", None, "compile_hit_rate"),
    ("shed rate", None, "shed_rate"),
    ("obs ms/dispatch", None, "obs_overhead_ms_per_dispatch"),
    ("quality ms/dispatch", None, "quality_overhead_ms_per_dispatch"),
    ("achieved TFLOPS", None, "achieved_tflops"),
    ("fleet jobs/min 2rep", "fleet", "jobs_per_min_2rep"),
    ("fleet jobs/min 1rep", "fleet", "jobs_per_min_1rep"),
    ("fleet p50 s", "fleet", "p50_latency_s_2rep"),
    ("fleet p99 s", "fleet", "p99_latency_s_2rep"),
    ("fleet affinity", "fleet", "affinity_hit_rate"),
    ("fleet jobs/min obs", "fleet", "jobs_per_min_2rep_obs"),
    ("gateway obs ms/job", "fleet", "gateway_overhead_ms_per_job"),
    ("flight ms/dispatch", "flight",
     "flight_overhead_ms_per_dispatch"),
    ("flight dump p50 s", "flight", "dump_p50_s"),
    ("flight ring hw B", "flight", "span_ring_bytes_hw"),
    ("flight bundles", "flight", "bundles_written"),
    ("usage ms/dispatch", "usage",
     "usage_overhead_ms_per_dispatch"),
    ("usage conserved", "usage", "conservation_holds"),
    ("usage tenants", "usage", "tenants_metered"),
    ("scale jobs/min auto", "scale", "jobs_per_min_scaled"),
    ("scale jobs/min fixed", "scale", "jobs_per_min_fixed"),
    ("scale p99 s auto", "scale", "p99_latency_s_scaled"),
    ("scale ups", "scale", "scale_ups"),
    ("scale downs", "scale", "scale_downs"),
    ("scale jobs lost", "scale", "jobs_lost"),
    ("scale identical", "scale", "records_identical"),
    # extra.serve_mesh (ISSUE 17): dotted legs descend into the A/B's
    # sub-objects — jobs_per_min appears in all three, so a flat
    # lookup would pick whichever leg happened to survive truncation
    ("devices", "serve_mesh.ndev_parked", "devices"),
    ("mesh jobs/min 1dev", "serve_mesh.1dev_parked", "jobs_per_min"),
    ("mesh jobs/min ndev", "serve_mesh.ndev_parked", "jobs_per_min"),
    ("mesh jobs/min resident", "serve_mesh.ndev_resident",
     "jobs_per_min"),
    ("mesh gap ms/q resident", "serve_mesh.ndev_resident",
     "host_gap_ms_per_quantum"),
    ("mesh B/q resident", "serve_mesh.ndev_resident",
     "park_resume_bytes_per_quantum"),
    ("mesh B/q parked", "serve_mesh.ndev_parked",
     "park_resume_bytes_per_quantum"),
    ("scale compile attempts", "scale_2000ev", "compile_attempts"),
    # extra.accord (ISSUE 18): the control side channel's cost when
    # nothing is wrong — single-process A/B identity plus the loopback
    # agreement microbench's per-fence overhead
    ("accord ms/agree", "accord", "agree_ms_per_fence"),
    ("accord ms/guard", "accord", "guard_ms_per_fence"),
    ("accord identical", "accord", "records_identical"),
    # extra.edit (ISSUE 19, tt-edit): warm vs cold incremental
    # re-solve — generations to reach the base job's final quality,
    # the anchored stability (events moved vs the base timetable), the
    # same-bucket no-demotion pin, and the w_anchor=0 stream identity
    ("edit gens-to-base warm", "edit.warm", "gens_to_base_quality"),
    ("edit gens-to-base cold", "edit.cold", "gens_to_base_quality"),
    ("edit t-feas warm s", "edit.warm", "time_to_feasible_s"),
    ("edit distance warm", "edit.warm", "edit_distance"),
    ("edit demoted warm", "edit.warm", "demoted"),
    ("edit identical w0", "edit", "records_identical_w0"),
    # extra.prof (ISSUE 20, tt-prof): profiler-capture overhead on the
    # dispatch loop, where the attributed device time went (the item-4
    # attack order), the honest unattributed remainder, and the
    # capture-off/on stream identity
    ("prof ms/dispatch", "prof", "prof_overhead_ms_per_dispatch"),
    ("prof frac rooms", "prof", "frac_rooms"),
    ("prof frac sweep", "prof", "frac_sweep"),
    ("prof frac fitness", "prof", "frac_fitness"),
    ("prof unattributed", "prof", "unattributed_frac"),
    ("prof identical", "prof", "records_identical_modulo_timing"),
]

_NUM = r"(-?\d+(?:\.\d+)?(?:[eE][-+]?\d+)?)"


def _decode_tail_json(tail: str):
    """Largest decodable JSON object in a (possibly head-clipped)
    tail: try json.loads from every '{' (earliest first — the
    outermost surviving object wins)."""
    for m in re.finditer(r"\{", tail):
        chunk = tail[m.start():]
        try:
            obj = json.loads(chunk)
        except ValueError:
            continue
        if isinstance(obj, dict):
            return obj
    return None


def _flatten(obj, out=None):
    out = {} if out is None else out
    if isinstance(obj, dict):
        for k, v in obj.items():
            if isinstance(v, (dict, list)):
                _flatten(v, out)
            elif isinstance(v, (int, float)) and k not in out:
                out[k] = v
    elif isinstance(obj, list):
        for v in obj:
            _flatten(v, out)
    return out


def _metric(doc, text: str, leg, key):
    """One metric's value for a round: the decoded JSON when it
    survived, else a regex over the raw text. Leg-scoped lookups search
    only inside that leg's object (both paths), so a truncated round
    can never substitute another leg's same-named key."""
    if leg is None:
        if isinstance(doc, dict):
            flat = _flatten(doc)
            if key in flat:
                return float(flat[key])
        m = re.search(rf'"{key}":\s*{_NUM}', text)
        return float(m.group(1)) if m else None
    if isinstance(doc, dict):
        obj = doc
        for part in leg.split("."):
            nxt = obj.get(part) if isinstance(obj, dict) else None
            if (nxt is None and isinstance(obj, dict)
                    and isinstance(obj.get("extra"), dict)):
                nxt = obj["extra"].get(part)
            obj = nxt
        if isinstance(obj, dict) and key in obj:
            return float(obj[key])
    inner = leg.split(".")[-1]
    m = re.search(rf'"{inner}":\s*\{{[^}}]*"{key}":\s*{_NUM}', text)
    return float(m.group(1)) if m else None


def load_bench_round(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        cap = json.load(f)
    tail = cap.get("tail") or ""
    doc = cap.get("parsed")
    if not isinstance(doc, dict):
        doc = _decode_tail_json(tail)
    metrics: dict = {}
    for header, leg, key in _METRICS:
        v = _metric(doc, tail, leg, key)
        if v is not None:
            metrics[header] = v
    return {"round": cap.get("n"), "rc": cap.get("rc"),
            "metrics": metrics,
            "salvage": ("parsed" if isinstance(cap.get("parsed"), dict)
                        else "tail-json" if isinstance(doc, dict)
                        else "regex")}


def load_multichip_round(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        cap = json.load(f)
    tail = (cap.get("tail") or "").strip()
    m = re.search(r"(?:global_)?best=(\d+)", tail)
    g = re.search(r"gens=(\d+)", tail)
    return {"round": int(re.search(r"_r0*(\d+)", path).group(1)),
            "n_devices": cap.get("n_devices"), "ok": cap.get("ok"),
            "best": int(m.group(1)) if m else None,
            "gens": int(g.group(1)) if g else None}


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float) and v != int(v):
        return f"{v:.3g}"
    return str(int(v) if isinstance(v, float) else v)


def report(root: str = REPO) -> str:
    bench_paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
    multi_paths = sorted(glob.glob(os.path.join(root,
                                                "MULTICHIP_r*.json")))
    rounds = [load_bench_round(p) for p in bench_paths]
    multis = [load_multichip_round(p) for p in multi_paths]
    lines = []
    if rounds:
        headers = ["round"] + [h for h, _, _ in _METRICS] + ["salvage"]
        lines.append("| " + " | ".join(headers) + " |")
        lines.append("|" + "---|" * len(headers))
        for r in rounds:
            row = [f"r{_fmt(r['round'])}"]
            for header, _, _ in _METRICS:
                row.append(_fmt(r["metrics"].get(header)))
            row.append(r["salvage"])
            lines.append("| " + " | ".join(row) + " |")
    else:
        lines.append("no BENCH_r*.json rounds found")
    lines.append("")
    if multis:
        lines.append("| round | devices | multichip ok | best | gens |")
        lines.append("|---|---|---|---|---|")
        for m in multis:
            lines.append(
                f"| r{_fmt(m['round'])} | {_fmt(m['n_devices'])} | "
                f"{'yes' if m['ok'] else 'NO'} | {_fmt(m['best'])} | "
                f"{_fmt(m['gens'])} |")
    lines.append("")
    lines.extend(_scaling_section(rounds, multis))
    return "\n".join(lines)


def _scaling_section(rounds, multis) -> list:
    """Throughput-vs-device-count curves, per round: the serve_mesh
    A/B's jobs/min spread (1-device baseline vs the full mesh vs the
    resident full mesh), the headline gens/s trajectory, and the
    multichip dry-run's device widths — the at-a-glance answer to
    'does adding devices still buy throughput'."""
    lines = ["## scaling curves (throughput vs devices)"]
    mesh_rows = []
    for r in rounds:
        m = r["metrics"]
        one = m.get("mesh jobs/min 1dev")
        nd = m.get("mesh jobs/min ndev")
        if one is None and nd is None:
            continue
        dev = m.get("devices")
        speedup = (f" ({nd / one:.2f}x)"
                   if one and nd else "")
        mesh_rows.append(
            f"  r{_fmt(r['round'])}: 1dev {_fmt(one)} -> "
            f"{_fmt(dev)}dev {_fmt(nd)} jobs/min{speedup}, resident "
            f"{_fmt(m.get('mesh jobs/min resident'))}")
    if mesh_rows:
        lines.append("jobs/min (extra.serve_mesh, 1-device vs full "
                     "mesh vs full mesh + resident groups):")
        lines.extend(mesh_rows)
    else:
        lines.append("jobs/min: no extra.serve_mesh legs recorded yet")
    gens = [(r["round"], r["metrics"].get("gens/s scan"))
            for r in rounds
            if r["metrics"].get("gens/s scan") is not None]
    if gens:
        lines.append("gens/s (generation_scan) per round: "
                     + ", ".join(f"r{_fmt(n)} {_fmt(v)}"
                                 for n, v in gens))
    # tt-accord (ISSUE 18): the multi-host control channel's per-fence
    # host overhead next to the curves it enables — a multi-host run
    # pays this per agreement fence, off the device path
    accord = [(r["round"], r["metrics"].get("accord ms/agree"),
               r["metrics"].get("accord identical"))
              for r in rounds
              if r["metrics"].get("accord ms/agree") is not None]
    if accord:
        lines.append("accord fence overhead (extra.accord, loopback "
                     "2-view): "
                     + ", ".join(
                         f"r{_fmt(n)} {_fmt(v)} ms/agree"
                         f" identical={'yes' if ident else 'NO'}"
                         for n, v, ident in accord))
    # tt-edit (ISSUE 19): warm-start leverage per round — how many
    # generations the transplanted population saves on the way back to
    # the base job's quality (the at-scale traffic is mostly edits)
    edit = [(r["round"], r["metrics"].get("edit gens-to-base warm"),
             r["metrics"].get("edit gens-to-base cold"),
             r["metrics"].get("edit demoted warm"))
            for r in rounds
            if r["metrics"].get("edit gens-to-base warm") is not None]
    if edit:
        lines.append("edit warm-start (extra.edit, gens to base "
                     "quality warm vs cold): "
                     + ", ".join(
                         f"r{_fmt(n)} {_fmt(w)} vs {_fmt(c)}"
                         f" demoted={_fmt(d)}"
                         for n, w, c, d in edit))
    if multis:
        lines.append("multichip dry-run (devices -> gens): "
                     + ", ".join(
                         f"r{_fmt(m['round'])} "
                         f"{_fmt(m['n_devices'])}dev "
                         f"gens={_fmt(m['gens'])}" for m in multis))
    # gens/s vs devices AND hosts (ROADMAP item 2): one curve per
    # round from whatever width legs that round recorded — the
    # 1-device generation_parallel point, the full-mesh width the
    # serve_mesh leg proved, and the multichip dry-run width. Rounds
    # with no multi-host leg say so explicitly rather than letting a
    # single-host curve read as a scaling result.
    by_round_dev = {m["round"]: m["n_devices"] for m in multis}
    curve_rows = []
    for r in rounds:
        m = r["metrics"]
        g1 = m.get("gens/s parallel")
        if g1 is None:
            continue
        pts = [f"1dev {_fmt(g1)} gens/s"]
        ndev = m.get("devices") or by_round_dev.get(r["round"])
        if ndev and ndev > 1:
            pts.append(f"widest proven {_fmt(ndev)}dev")
        curve_rows.append(f"  r{_fmt(r['round'])}: " + ", ".join(pts))
    if curve_rows:
        lines.append("gens/s vs devices/hosts (generation_parallel "
                     "point + widest proven mesh; no multi-HOST "
                     "throughput leg recorded yet — item 2's open "
                     "half):")
        lines.extend(curve_rows)
    # tt-prof (ISSUE 20): where the attributed device time went, per
    # round — the phase mix that orders the item-4 kernel attacks
    prof = [(r["round"], r["metrics"].get("prof frac rooms"),
             r["metrics"].get("prof frac sweep"),
             r["metrics"].get("prof frac fitness"),
             r["metrics"].get("prof unattributed"))
            for r in rounds
            if r["metrics"].get("prof frac rooms") is not None]
    if prof:
        lines.append("phase mix (extra.prof, fraction of attributed "
                     "device time): "
                     + ", ".join(
                         f"r{_fmt(n)} rooms {_fmt(ro)} sweep "
                         f"{_fmt(sw)} fitness {_fmt(fi)} "
                         f"unattributed {_fmt(ua)}"
                         for n, ro, sw, fi, ua in prof))
    return lines


def metrics_report(path: str) -> str:
    """Render a saved exposition snapshot (Prometheus 0.0.4 or
    OpenMetrics 1.0 text) as a readable table — the shared parser
    (obs/scrape.py) is the only consumer-side knowledge of the
    format. Histogram families collapse to their _count/_sum samples
    plus any bucket exemplars (the job/dispatch a latency spike joins
    back to)."""
    sys.path.insert(0, REPO)
    from timetabling_ga_tpu.obs import scrape as obs_scrape
    with open(path, encoding="utf-8") as f:
        text = f.read()
    families = obs_scrape.parse_exposition(text)
    lines = [f"# metrics snapshot: {os.path.basename(path)} "
             f"({len(families)} sample families)"]
    for name in sorted(families):
        if name.endswith("_bucket"):
            continue               # buckets fold into _count/_sum
        for labels, value in families[name]:
            lbl = ("{" + ",".join(f"{k}={v}" for k, v in
                                  sorted(labels.items())) + "}"
                   if labels else "")
            lines.append(f"  {name}{lbl} = {_fmt(value)}")
    exemplars = obs_scrape.parse_exemplars(text)
    if exemplars:
        lines.append("  exemplars:")
        for name, labels, v in exemplars:
            lbl = ",".join(f"{k}={w}" for k, w in
                           sorted(labels.items()))
            lines.append(f"    {name} <- {{{lbl}}} {_fmt(v)}")
    return "\n".join(lines)


def main(argv) -> int:
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    if "--metrics" in argv:
        i = argv.index("--metrics")
        if i + 1 >= len(argv):
            print("--metrics needs a snapshot file", file=sys.stderr)
            return 2
        print(metrics_report(argv[i + 1]))
        return 0
    root = argv[0] if argv else REPO
    if as_json:
        rounds = [load_bench_round(p) for p in
                  sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))]
        multis = [load_multichip_round(p) for p in
                  sorted(glob.glob(os.path.join(root,
                                                "MULTICHIP_r*.json")))]
        print(json.dumps({"bench": rounds, "multichip": multis},
                         indent=2))
    else:
        print(report(root))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
